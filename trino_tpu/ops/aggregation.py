"""Group-by aggregation via sort + sorted-segment reductions.

Reference semantics: ``operator/HashAggregationOperator.java:49`` +
``operator/MultiChannelGroupByHash.java:55`` (open-addressing hash group-by)
and the aggregation function triple input/combine/output
(``operator/aggregation/LongSumAggregation.java:29-55``).

TPU-first design: instead of a linear-probing hash table (scatter-heavy,
serial), we lexicographically sort rows by the group keys with ``lax.sort``
(TPU has a fast bitonic sort), mark group boundaries, assign dense group ids
with a cumulative sum, and reduce over the *sorted* segments — all
MXU/VPU-friendly, fully static shapes.

Scatter-free: XLA scatter (``segment_sum`` / ``.at[].set``) lowers to a
serialized update loop on TPU (~80ms per 1M rows measured vs ~1ms for a
cumsum). Because rows are already sorted by group, every reduction is
expressible without scatter:
- segment boundary positions compact to the front of one cheap
  ``(bool, int32)`` sort (see :class:`_SortedSegments`);
- integer sums are exclusive-cumsum differences at the boundaries (exact:
  int64 wraparound is modular, so boundary differences recover any
  segment sum that itself fits in 64 bits);
- min/max re-sort ``(group_id, masked value)`` — bitonic sort is ~40x
  cheaper than scatter here — and gather the first/last row per segment;
- group keys gather the first row of each segment.
Float sums keep ``segment_sum`` (a global cumsum would change rounding).

Partial/final split: the same kernel serves both; COUNT partials re-aggregate
with SUM, AVG decomposes into SUM+COUNT (exactly Trino's
input/combine/output contract for distributed aggregation).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from trino_tpu import types as T

# Supported aggregate kinds and their (partial, final-combine) decomposition.
# sum128 / sum128w are the exact 128-bit accumulation variants for wide
# DECIMAL results (narrow int64 input / wide (n,2) input respectively) —
# see trino_tpu.ops.decimal128 (UnscaledDecimal128Arithmetic semantics).
AGG_KINDS = ("sum", "count", "count_star", "min", "max", "avg")


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregate: kind + input channel (None for count(*))."""

    kind: str
    input_dtype: object | None = None  # storage dtype of the input


def _sortable_keys(keys: Sequence[tuple[jnp.ndarray, jnp.ndarray]], sel: jnp.ndarray):
    """Build lax.sort operand list: selection first (selected rows to the
    front), then per-key (valid, data) pairs so NULL keys form one group.
    Wide DECIMAL keys ((n, 2) lanes) contribute one operand per lane."""
    ops = [~sel]  # False (selected) sorts before True
    for data, valid in keys:
        ops.append(~valid)  # non-null first; all nulls group together
        if getattr(data, "ndim", 1) == 2:
            for lane in (data[:, 0], data[:, 1]):
                ops.append(jnp.where(valid, lane, jnp.zeros_like(lane)))
        else:
            ops.append(jnp.where(valid, data, jnp.zeros_like(data)))
    return ops


def group_aggregate(
    keys: Sequence[tuple[jnp.ndarray, jnp.ndarray]],
    sel: jnp.ndarray,
    agg_inputs: Sequence[tuple[jnp.ndarray, jnp.ndarray] | None],
    agg_specs: Sequence[AggSpec],
    max_groups: int,
):
    """Sort-based grouped aggregation.

    Args:
      keys: per key column (data, valid), each shape (n,).
      sel: bool (n,) — rows participating.
      agg_inputs: per agg (data, valid) or None for count(*).
      agg_specs: kinds aligned with agg_inputs.
      max_groups: static output capacity (groups beyond are dropped —
        caller must size from stats; overflow is reported).

    Returns:
      (group_key_data, group_key_valid): lists of (max_groups,) arrays
      agg_results: list of result arrays (max_groups,) —
        for 'avg' returns (sum, count) pair folded by caller
      num_groups: int32 scalar
      overflow: bool scalar (true if groups were dropped)
    """
    n = sel.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    # build sort operands, tracking each key's operand positions (wide
    # DECIMAL keys contribute two value lanes). A ``valid`` of None means
    # "no nulls": the validity sort lane and null-masking are skipped
    # entirely (each dropped bool lane is a full bitonic pass saved).
    ops = [~sel]
    key_pos: list = []  # (valid_idx | None, data_idx...)
    for data, valid in keys:
        if valid is None:
            vi = None
        else:
            vi = len(ops)
            ops.append(~valid)
        if getattr(data, "ndim", 1) == 2:
            di = (len(ops), len(ops) + 1)
            for lane in (data[:, 0], data[:, 1]):
                ops.append(
                    lane if valid is None
                    else jnp.where(valid, lane, jnp.zeros_like(lane))
                )
        else:
            di = (len(ops),)
            ops.append(
                data if valid is None
                else jnp.where(valid, data, jnp.zeros_like(data))
            )
        key_pos.append((vi, di))
    num_keys = len(ops)
    # aggregate inputs ride the sort as payload operands: bitonic payload
    # moves are near-contiguous vector ops, ~17x cheaper here than the
    # random 1M-row gathers a post-sort ``data[perm]`` would need
    payload: list = []
    payload_pos: dict[tuple, tuple] = {}
    for pair in agg_inputs:
        if pair is None:
            continue
        pid = (id(pair[0]), id(pair[1]))
        if pid in payload_pos:
            continue
        data, valid = pair
        base = num_keys + len(payload)
        wide = getattr(data, "ndim", 1) == 2
        lanes = [data[:, 0], data[:, 1]] if wide else [data]
        if valid is not None:
            lanes.append(valid)
        payload.extend(lanes)
        payload_pos[pid] = (wide, tuple(range(base, base + len(lanes))), valid is not None)
    sorted_ops = jax.lax.sort(tuple(ops) + tuple(payload), num_keys=num_keys)
    s_sel = ~sorted_ops[0]

    def _sorted_pair(pair):
        wide, pos, has_valid = payload_pos[(id(pair[0]), id(pair[1]))]
        sv = sorted_ops[pos[-1]] if has_valid else None
        if wide:
            return (
                jnp.stack([sorted_ops[pos[0]], sorted_ops[pos[1]]], axis=1),
                sv,
            )
        return sorted_ops[pos[0]], sv

    # boundary: first row, or any sort key changed vs previous row
    changed = idx == 0
    for k in sorted_ops[:num_keys]:
        prev = jnp.concatenate([k[:1], k[:-1]])
        changed = changed | (k != prev)
    changed = changed & s_sel
    group_id = jnp.cumsum(changed.astype(jnp.int32)) - 1
    # unselected rows sort past selected ones -> monotonic out-of-range id
    group_id = jnp.where(s_sel, group_id, max_groups)
    num_groups = jnp.sum(changed.astype(jnp.int32))
    overflow = num_groups > max_groups

    seg = _SortedSegments(changed, s_sel, group_id, num_groups, max_groups, n)

    # group key output: gather the first row of each segment
    out_key_data, out_key_valid = [], []
    for (data, valid), (vi, di) in zip(keys, key_pos):
        if vi is None:
            kv = seg.nonempty
        else:
            kv = seg.first(~sorted_ops[vi]) & seg.nonempty
        lanes_out = []
        for d_idx in di:
            s_data = sorted_ops[d_idx]
            lanes_out.append(
                jnp.where(seg.nonempty, seg.first(s_data), jnp.zeros((), s_data.dtype))
            )
        if len(lanes_out) == 2:
            out_key_data.append(jnp.stack(lanes_out, axis=1).astype(data.dtype))
        else:
            out_key_data.append(lanes_out[0].astype(data.dtype))
        out_key_valid.append(kv)

    results = []
    for spec, pair in zip(agg_specs, agg_inputs):
        if spec.kind == "count_star":
            results.append(seg.sizes.astype(jnp.int64))
            continue
        s_data, s_valid = _sorted_pair(pair)

        def vcount():
            if s_valid is None:
                return seg.sizes.astype(jnp.int64)
            return seg.sum(s_valid.astype(jnp.int64))

        if spec.kind in ("sum128", "sum128w"):
            from trino_tpu.ops import decimal128 as D

            cnt = vcount()
            if spec.kind == "sum128":
                limbs = D.narrow_limb_sums(s_data, s_valid, seg.sum)
            else:
                limbs = D.wide_limb_sums(
                    s_data[:, 0], s_data[:, 1], s_valid, seg.sum
                )
            results.append((limbs, cnt))
            continue
        if spec.kind == "count":
            results.append(vcount())
        elif spec.kind in ("sum", "avg"):
            contrib = (
                s_data if s_valid is None
                else jnp.where(s_valid, s_data, jnp.zeros_like(s_data))
            )
            ssum = seg.sum(contrib)
            # SQL: sum over empty/all-null group is NULL — caller uses cnt
            results.append((ssum, vcount()))
        elif spec.kind in ("min", "max"):
            cnt = vcount()
            if getattr(s_data, "ndim", 1) == 2:
                from trino_tpu.ops.decimal128 import sort_operands_wide

                hi, lo = s_data[:, 0], s_data[:, 1]
                ident = _max_ident(hi.dtype) if spec.kind == "min" else _min_ident(hi.dtype)
                hk, lk = sort_operands_wide(hi, lo)
                if s_valid is not None:
                    hk = jnp.where(s_valid, hk, ident)
                    lk = jnp.where(s_valid, lk, ident)
                bh, blk = seg.extreme2(hk, lk, spec.kind)
                from trino_tpu.ops.decimal128 import _SIGNBIT

                results.append((jnp.stack([bh, blk ^ _SIGNBIT], axis=1), cnt))
            else:
                ident = (
                    _max_ident(s_data.dtype)
                    if spec.kind == "min"
                    else _min_ident(s_data.dtype)
                )
                masked = (
                    s_data if s_valid is None
                    else jnp.where(s_valid, s_data, ident)
                )
                results.append((seg.extreme(masked, spec.kind), cnt))
        else:
            raise NotImplementedError(spec.kind)
    return (out_key_data, out_key_valid), results, num_groups, overflow


def _prefix_sum(x):
    """Inclusive prefix sum via a blocked two-level scan.

    ``jnp.cumsum`` lowers to one big reduce-window whose scoped-vmem
    allocation blows up inside TPU while-loops (the streaming chunk loop);
    scanning 512-row blocks keeps every window small, and the block-offset
    pass runs over n/512 elements."""
    n = x.shape[0]
    blk = 512
    if n <= blk or n % blk:
        return jnp.cumsum(x)
    xb = jnp.reshape(x, (n // blk, blk))
    within = jnp.cumsum(xb, axis=1)
    offsets = jnp.cumsum(within[:, -1])
    offsets = jnp.concatenate([jnp.zeros((1,), x.dtype), offsets[:-1]])
    return jnp.reshape(within + offsets[:, None], (n,))


class _SortedSegments:
    """Scatter-free reductions over rows sorted by a monotonic group id.

    ``starts[g]`` is the first sorted-row index of group ``g``; every
    reduction is then a cumsum difference or a boundary gather. Boundary
    positions come from one cheap ``(bool, int32)`` sort — stably sorting
    row indices by "is not a group boundary" compacts the boundary
    positions to the front (a ``searchsorted`` over the 1M-row group-id
    array costs ~5x more here: its binary-search rounds serialize, while
    one more bitonic sort rides the same fast path the main sort uses).
    """

    def __init__(self, changed, s_sel, group_id_sorted, num_groups,
                 max_groups: int, n: int):
        idx = jnp.arange(n, dtype=jnp.int32)
        g = min(max_groups + 1, n)
        _, pos = jax.lax.sort((~changed, idx), num_keys=1)
        pos = pos[:g]
        if g < max_groups + 1:  # tiny batch: fewer rows than groups
            pos = jnp.concatenate(
                [pos, jnp.zeros(max_groups + 1 - g, dtype=jnp.int32)]
            )
        n_sel = jnp.sum(s_sel.astype(jnp.int32))
        live = jnp.arange(max_groups + 1, dtype=jnp.int32) < num_groups
        self.starts = jnp.where(live, pos, n_sel)
        self.sizes = self.starts[1:] - self.starts[:-1]
        self.nonempty = self.sizes > 0
        self._gid = group_id_sorted
        self._max_groups = max_groups
        hi = max(n - 1, 0)
        self._first_idx = jnp.clip(self.starts[:-1], 0, hi)
        self._last_idx = jnp.clip(self.starts[1:] - 1, 0, hi)

    def first(self, x):
        """x gathered at each segment's first row (junk for empty segs)."""
        return x[self._first_idx]

    def sum(self, x):
        """Per-segment sum via exclusive-cumsum boundary differences.

        Exact for integers (modular wraparound cancels); floats keep the
        scatter path so per-segment rounding stays left-to-right instead
        of accumulating across the whole chunk.
        """
        import numpy as np

        if not np.issubdtype(np.dtype(x.dtype), np.integer):
            return jax.ops.segment_sum(
                x, self._gid, num_segments=self._max_groups
            )
        cs = _prefix_sum(x)
        csz = jnp.concatenate([jnp.zeros((1,), x.dtype), cs])
        return csz[self.starts[1:]] - csz[self.starts[:-1]]

    def extreme(self, masked, kind: str):
        """Per-segment min/max of pre-masked values via one extra sort."""
        _, sv = jax.lax.sort((self._gid, masked), num_keys=2)
        return sv[self._first_idx] if kind == "min" else sv[self._last_idx]

    def extreme2(self, k1, k2, kind: str):
        """Lexicographic two-lane min/max (wide DECIMAL) via one sort."""
        _, s1, s2 = jax.lax.sort((self._gid, k1, k2), num_keys=3)
        i = self._first_idx if kind == "min" else self._last_idx
        return s1[i], s2[i]


def distinct_first_mask(
    keys: Sequence[tuple[jnp.ndarray, jnp.ndarray]],
    value: tuple[jnp.ndarray, jnp.ndarray],
    sel: jnp.ndarray,
) -> jnp.ndarray:
    """Mask of first occurrences of each (group keys..., value) combination
    among selected rows — the dedup pass behind DISTINCT aggregates
    (reference: ``MarkDistinctOperator.java`` / distinct accumulators).

    Sort-based: lexicographically sort (sel, keys..., value), mark rows where
    any component differs from the previous row, and restore original row
    order with a second (scatter-free) sort on the permutation.
    """
    n = sel.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    ops = _sortable_keys(list(keys) + [value], sel)
    num_keys = len(ops)
    sorted_ops = jax.lax.sort(tuple(ops) + (idx,), num_keys=num_keys)
    perm = sorted_ops[-1]
    s_sel = ~sorted_ops[0]
    changed = idx == 0
    for k in sorted_ops[:num_keys]:
        prev = jnp.concatenate([k[:1], k[:-1]])
        changed = changed | (k != prev)
    first_sorted = changed & s_sel
    # invert the permutation with a second sort (scatter-free): sorting
    # (perm, mask) by perm restores original row order for the mask
    _, out = jax.lax.sort((perm, first_sorted), num_keys=1)
    return out


def global_aggregate(
    sel: jnp.ndarray,
    agg_inputs: Sequence[tuple[jnp.ndarray, jnp.ndarray] | None],
    agg_specs: Sequence[AggSpec],
):
    """Aggregation without GROUP BY: single group, plain reductions."""
    results = []
    for spec, pair in zip(agg_specs, agg_inputs):
        if spec.kind == "count_star":
            results.append(jnp.sum(sel.astype(jnp.int64)))
            continue
        data, valid = pair
        use = sel if valid is None else (valid & sel)
        cnt = jnp.sum(use.astype(jnp.int64))
        if spec.kind in ("sum128", "sum128w"):
            from trino_tpu.ops import decimal128 as D

            total = lambda x: jnp.reshape(jnp.sum(x), (1,))  # noqa: E731
            if spec.kind == "sum128":
                limbs = D.narrow_limb_sums(data, use, total)
            else:
                limbs = D.wide_limb_sums(data[:, 0], data[:, 1], use, total)
            results.append((limbs, cnt))
            continue
        if spec.kind == "count":
            results.append(cnt)
        elif spec.kind in ("sum", "avg"):
            s = jnp.sum(jnp.where(use, data, jnp.zeros_like(data)))
            results.append((s, cnt))
        elif spec.kind in ("min", "max") and getattr(data, "ndim", 1) == 2:
            from trino_tpu.ops.decimal128 import global_minmax_wide

            bh, bl = global_minmax_wide(data[:, 0], data[:, 1], use, spec.kind)
            results.append((jnp.stack([bh, bl], axis=1), cnt))
        elif spec.kind == "min":
            results.append((jnp.min(jnp.where(use, data, _max_ident(data.dtype))), cnt))
        elif spec.kind == "max":
            results.append((jnp.max(jnp.where(use, data, _min_ident(data.dtype))), cnt))
        else:
            raise NotImplementedError(spec.kind)
    return results


def _max_ident(dtype):
    import numpy as np

    if np.issubdtype(dtype, np.integer):
        return jnp.asarray(np.iinfo(dtype).max, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.asarray(True)
    return jnp.asarray(np.inf, dtype=dtype)


def _min_ident(dtype):
    import numpy as np

    if np.issubdtype(dtype, np.integer):
        return jnp.asarray(np.iinfo(dtype).min, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.asarray(False)
    return jnp.asarray(-np.inf, dtype=dtype)
