"""Group-by aggregation via sort + segment-reduce.

Reference semantics: ``operator/HashAggregationOperator.java:49`` +
``operator/MultiChannelGroupByHash.java:55`` (open-addressing hash group-by)
and the aggregation function triple input/combine/output
(``operator/aggregation/LongSumAggregation.java:29-55``).

TPU-first design: instead of a linear-probing hash table (scatter-heavy,
serial), we lexicographically sort rows by the group keys with ``lax.sort``
(TPU has a fast bitonic sort), mark group boundaries, assign dense group ids
with a cumulative sum, and reduce with ``jax.ops.segment_sum``-family ops —
all MXU/VPU-friendly, fully static shapes.

Partial/final split: the same kernel serves both; COUNT partials re-aggregate
with SUM, AVG decomposes into SUM+COUNT (exactly Trino's
input/combine/output contract for distributed aggregation).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from trino_tpu import types as T

# Supported aggregate kinds and their (partial, final-combine) decomposition.
# sum128 / sum128w are the exact 128-bit accumulation variants for wide
# DECIMAL results (narrow int64 input / wide (n,2) input respectively) —
# see trino_tpu.ops.decimal128 (UnscaledDecimal128Arithmetic semantics).
AGG_KINDS = ("sum", "count", "count_star", "min", "max", "avg")


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregate: kind + input channel (None for count(*))."""

    kind: str
    input_dtype: object | None = None  # storage dtype of the input


def _sortable_keys(keys: Sequence[tuple[jnp.ndarray, jnp.ndarray]], sel: jnp.ndarray):
    """Build lax.sort operand list: selection first (selected rows to the
    front), then per-key (valid, data) pairs so NULL keys form one group.
    Wide DECIMAL keys ((n, 2) lanes) contribute one operand per lane."""
    ops = [~sel]  # False (selected) sorts before True
    for data, valid in keys:
        ops.append(~valid)  # non-null first; all nulls group together
        if getattr(data, "ndim", 1) == 2:
            for lane in (data[:, 0], data[:, 1]):
                ops.append(jnp.where(valid, lane, jnp.zeros_like(lane)))
        else:
            ops.append(jnp.where(valid, data, jnp.zeros_like(data)))
    return ops


def group_aggregate(
    keys: Sequence[tuple[jnp.ndarray, jnp.ndarray]],
    sel: jnp.ndarray,
    agg_inputs: Sequence[tuple[jnp.ndarray, jnp.ndarray] | None],
    agg_specs: Sequence[AggSpec],
    max_groups: int,
):
    """Sort-based grouped aggregation.

    Args:
      keys: per key column (data, valid), each shape (n,).
      sel: bool (n,) — rows participating.
      agg_inputs: per agg (data, valid) or None for count(*).
      agg_specs: kinds aligned with agg_inputs.
      max_groups: static output capacity (groups beyond are dropped —
        caller must size from stats; overflow is reported).

    Returns:
      (group_key_data, group_key_valid): lists of (max_groups,) arrays
      agg_results: list of result arrays (max_groups,) —
        for 'avg' returns (sum, count) pair folded by caller
      num_groups: int32 scalar
      overflow: bool scalar (true if groups were dropped)
    """
    n = sel.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    # build sort operands, tracking each key's operand positions (wide
    # DECIMAL keys contribute two value lanes)
    ops = [~sel]
    key_pos: list[tuple[int, tuple[int, ...]]] = []  # (valid_idx, data_idx...)
    for data, valid in keys:
        vi = len(ops)
        ops.append(~valid)
        if getattr(data, "ndim", 1) == 2:
            di = (len(ops), len(ops) + 1)
            for lane in (data[:, 0], data[:, 1]):
                ops.append(jnp.where(valid, lane, jnp.zeros_like(lane)))
        else:
            di = (len(ops),)
            ops.append(jnp.where(valid, data, jnp.zeros_like(data)))
        key_pos.append((vi, di))
    num_keys = len(ops)
    sorted_ops = jax.lax.sort(tuple(ops) + (idx,), num_keys=num_keys)
    perm = sorted_ops[-1]
    s_sel = ~sorted_ops[0]

    # boundary: first row, or any sort key changed vs previous row
    changed = jnp.zeros(n, dtype=jnp.bool_).at[0].set(True)
    for k in sorted_ops[:num_keys]:
        prev = jnp.concatenate([k[:1], k[:-1]])
        changed = changed | (k != prev)
    changed = changed & s_sel
    group_id = jnp.cumsum(changed.astype(jnp.int32)) - 1
    # unselected rows -> out-of-range id (dropped by segment ops/'drop' mode)
    group_id = jnp.where(s_sel, group_id, max_groups)
    num_groups = jnp.sum(changed.astype(jnp.int32))
    overflow = num_groups > max_groups

    # group key output: scatter first-row-of-group values
    out_key_data, out_key_valid = [], []
    for (data, valid), (vi, di) in zip(keys, key_pos):
        s_valid = ~sorted_ops[vi]
        kv = jnp.zeros((max_groups,), dtype=jnp.bool_).at[group_id].set(
            s_valid, mode="drop"
        )
        lanes_out = []
        for d_idx in di:
            s_data = sorted_ops[d_idx]
            lanes_out.append(
                jnp.zeros((max_groups,), dtype=s_data.dtype).at[group_id].set(
                    s_data, mode="drop"
                )
            )
        if len(lanes_out) == 2:
            out_key_data.append(jnp.stack(lanes_out, axis=1).astype(data.dtype))
        else:
            out_key_data.append(lanes_out[0].astype(data.dtype))
        out_key_valid.append(kv)

    results = []
    for spec, pair in zip(agg_specs, agg_inputs):
        if spec.kind == "count_star":
            ones = jnp.ones(n, dtype=jnp.int64)
            results.append(
                jax.ops.segment_sum(ones, group_id, num_segments=max_groups)
            )
            continue
        data, valid = pair
        s_data = data[perm]
        s_valid = valid[perm]
        if spec.kind in ("sum128", "sum128w"):
            from trino_tpu.ops import decimal128 as D

            cnt = jax.ops.segment_sum(
                s_valid.astype(jnp.int64), group_id, num_segments=max_groups
            )
            if spec.kind == "sum128":
                limbs = D.narrow_limb_sums(s_data, s_valid, group_id, max_groups)
            else:
                limbs = D.wide_limb_sums(
                    s_data[:, 0], s_data[:, 1], s_valid, group_id, max_groups
                )
            results.append((limbs, cnt))
            continue
        if spec.kind == "count":
            results.append(
                jax.ops.segment_sum(
                    s_valid.astype(jnp.int64), group_id, num_segments=max_groups
                )
            )
        elif spec.kind in ("sum", "avg"):
            contrib = jnp.where(s_valid, s_data, jnp.zeros_like(s_data))
            ssum = jax.ops.segment_sum(contrib, group_id, num_segments=max_groups)
            if spec.kind == "sum":
                cnt = jax.ops.segment_sum(
                    s_valid.astype(jnp.int64), group_id, num_segments=max_groups
                )
                # SQL: sum over empty/all-null group is NULL — caller uses cnt
                results.append((ssum, cnt))
            else:
                cnt = jax.ops.segment_sum(
                    s_valid.astype(jnp.int64), group_id, num_segments=max_groups
                )
                results.append((ssum, cnt))
        elif spec.kind in ("min", "max"):
            cnt = jax.ops.segment_sum(
                s_valid.astype(jnp.int64), group_id, num_segments=max_groups
            )
            if getattr(s_data, "ndim", 1) == 2:
                from trino_tpu.ops.decimal128 import segment_minmax_wide

                bh, bl = segment_minmax_wide(
                    s_data[:, 0], s_data[:, 1], s_valid, group_id,
                    max_groups, spec.kind,
                )
                results.append((jnp.stack([bh, bl], axis=1), cnt))
            elif spec.kind == "min":
                masked = jnp.where(s_valid, s_data, _max_ident(s_data.dtype))
                m = jax.ops.segment_min(masked, group_id, num_segments=max_groups)
                results.append((m, cnt))
            else:
                masked = jnp.where(s_valid, s_data, _min_ident(s_data.dtype))
                m = jax.ops.segment_max(masked, group_id, num_segments=max_groups)
                results.append((m, cnt))
        else:
            raise NotImplementedError(spec.kind)
    return (out_key_data, out_key_valid), results, num_groups, overflow


def distinct_first_mask(
    keys: Sequence[tuple[jnp.ndarray, jnp.ndarray]],
    value: tuple[jnp.ndarray, jnp.ndarray],
    sel: jnp.ndarray,
) -> jnp.ndarray:
    """Mask of first occurrences of each (group keys..., value) combination
    among selected rows — the dedup pass behind DISTINCT aggregates
    (reference: ``MarkDistinctOperator.java`` / distinct accumulators).

    Sort-based: lexicographically sort (sel, keys..., value), mark rows where
    any component differs from the previous row, and scatter the marks back
    through the permutation.
    """
    n = sel.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    ops = _sortable_keys(list(keys) + [value], sel)
    num_keys = len(ops)
    sorted_ops = jax.lax.sort(tuple(ops) + (idx,), num_keys=num_keys)
    perm = sorted_ops[-1]
    s_sel = ~sorted_ops[0]
    changed = jnp.zeros(n, dtype=jnp.bool_).at[0].set(True)
    for k in sorted_ops[:num_keys]:
        prev = jnp.concatenate([k[:1], k[:-1]])
        changed = changed | (k != prev)
    first_sorted = changed & s_sel
    return jnp.zeros(n, dtype=jnp.bool_).at[perm].set(first_sorted)


def global_aggregate(
    sel: jnp.ndarray,
    agg_inputs: Sequence[tuple[jnp.ndarray, jnp.ndarray] | None],
    agg_specs: Sequence[AggSpec],
):
    """Aggregation without GROUP BY: single group, plain reductions."""
    results = []
    for spec, pair in zip(agg_specs, agg_inputs):
        if spec.kind == "count_star":
            results.append(jnp.sum(sel.astype(jnp.int64)))
            continue
        data, valid = pair
        use = valid & sel
        cnt = jnp.sum(use.astype(jnp.int64))
        if spec.kind in ("sum128", "sum128w"):
            from trino_tpu.ops import decimal128 as D

            gid = jnp.zeros(sel.shape[0], dtype=jnp.int32)
            if spec.kind == "sum128":
                limbs = D.narrow_limb_sums(data, use, gid, 1)
            else:
                limbs = D.wide_limb_sums(data[:, 0], data[:, 1], use, gid, 1)
            results.append((limbs, cnt))
            continue
        if spec.kind == "count":
            results.append(cnt)
        elif spec.kind in ("sum", "avg"):
            s = jnp.sum(jnp.where(use, data, jnp.zeros_like(data)))
            results.append((s, cnt))
        elif spec.kind in ("min", "max") and getattr(data, "ndim", 1) == 2:
            from trino_tpu.ops.decimal128 import global_minmax_wide

            bh, bl = global_minmax_wide(data[:, 0], data[:, 1], use, spec.kind)
            results.append((jnp.stack([bh, bl], axis=1), cnt))
        elif spec.kind == "min":
            results.append((jnp.min(jnp.where(use, data, _max_ident(data.dtype))), cnt))
        elif spec.kind == "max":
            results.append((jnp.max(jnp.where(use, data, _min_ident(data.dtype))), cnt))
        else:
            raise NotImplementedError(spec.kind)
    return results


def _max_ident(dtype):
    import numpy as np

    if np.issubdtype(dtype, np.integer):
        return jnp.asarray(np.iinfo(dtype).max, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.asarray(True)
    return jnp.asarray(np.inf, dtype=dtype)


def _min_ident(dtype):
    import numpy as np

    if np.issubdtype(dtype, np.integer):
        return jnp.asarray(np.iinfo(dtype).min, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.asarray(False)
    return jnp.asarray(-np.inf, dtype=dtype)
