"""Window function kernels.

Reference: ``operator/WindowOperator.java:67`` + the 21 window function
implementations under ``operator/window/`` (``RowNumberFunction.java``,
``RankFunction.java``, ``DenseRankFunction.java``, ``NTileFunction.java``,
``LagFunction.java``/``LeadFunction.java``, ``FirstValueFunction.java``,
``LastValueFunction.java``, aggregate-over-window via
``AggregateWindowFunction.java``).

TPU-first design: Trino's WindowOperator sorts a PagesIndex by
(partition, order) keys and walks partitions row-at-a-time. Here the whole
batch is processed as ONE fused device program:

1. a single multi-key ``lax.sort`` puts rows in (partition, order) order
   (unselected rows sink to the end);
2. partition/peer boundaries become boolean flag vectors;
3. every window function is a *segmented scan* (``lax.associative_scan``
   with a reset-at-flag combiner) or a gather off the running values;
4. results scatter back to original row positions with one ``.at[perm]``.

No per-partition loop, no dynamic shapes — one O(n log n) sort plus O(n)
scans, all MXU/VPU-friendly.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu.ops.sort import SortKey, sortable_key


@dataclasses.dataclass(frozen=True)
class WindowSpecKernel:
    """Which frame the aggregate kinds use (ranking kinds ignore it)."""

    # "running_range": UNBOUNDED PRECEDING..CURRENT ROW in RANGE mode
    #   (includes peers of the current row — the SQL default with ORDER BY)
    # "running_rows": same in ROWS mode (exactly the rows up to current)
    # "partition": whole partition (the default when there is no ORDER BY,
    #   or an explicit UNBOUNDED PRECEDING..UNBOUNDED FOLLOWING frame)
    # "rows_preceding": ROWS BETWEEN k PRECEDING AND CURRENT ROW
    frame: str = "running_range"
    preceding: int = 0  # k for rows_preceding


@dataclasses.dataclass(frozen=True)
class WindowFn:
    kind: str  # row_number|rank|dense_rank|ntile|lead|lag|first_value|last_value|sum|count|count_star|avg|min|max
    offset: int = 1  # lead/lag distance; ntile bucket count
    has_default: bool = False  # lead/lag with explicit default


def _ne_prev(data: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """flag[i] = row i differs from row i-1 on this key (NULLs compare equal).
    flag[0] = True."""
    d = jnp.where(valid, data, jnp.zeros_like(data))
    dv = jnp.concatenate([jnp.ones(1, dtype=jnp.bool_), d[1:] != d[:-1]])
    vv = jnp.concatenate([jnp.ones(1, dtype=jnp.bool_), valid[1:] != valid[:-1]])
    return dv | vv


def _segmented_scan(values: jnp.ndarray, seg_start: jnp.ndarray, combine):
    """Inclusive segmented scan: prefix-``combine`` resetting at seg_start."""

    def op(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, combine(va, vb))

    _, out = jax.lax.associative_scan(op, (seg_start, values))
    return out


def _running_max_idx(flag: jnp.ndarray, n: int) -> jnp.ndarray:
    """For each i, the largest j<=i with flag[j] (flag[0] must be True)."""
    idx = jnp.arange(n, dtype=jnp.int32)
    return jax.lax.associative_scan(jnp.maximum, jnp.where(flag, idx, 0))


def _next_flag_idx(flag: jnp.ndarray, n: int) -> jnp.ndarray:
    """For each i, the smallest j>i with flag[j], else n."""
    idx = jnp.arange(n, dtype=jnp.int32)
    a = jnp.where(flag, idx, n)
    suffix_min = jax.lax.associative_scan(jnp.minimum, a, reverse=True)
    return jnp.concatenate(
        [suffix_min[1:], jnp.full(1, n, dtype=suffix_min.dtype)]
    ).astype(jnp.int32)


def compute_windows(
    partition_keys: Sequence[tuple[jnp.ndarray, jnp.ndarray]],
    partition_ranks: Sequence[Optional[np.ndarray]],
    order_keys: Sequence[tuple[jnp.ndarray, jnp.ndarray]],
    order_specs: Sequence[SortKey],
    order_ranks: Sequence[Optional[np.ndarray]],
    sel: jnp.ndarray,
    functions: Sequence[WindowFn],
    fn_args: Sequence[Optional[tuple[jnp.ndarray, jnp.ndarray]]],
    fn_defaults: Sequence[Optional[tuple[jnp.ndarray, jnp.ndarray]]],
    frame: WindowSpecKernel,
):
    """Evaluate all window functions sharing one (partition, order, frame)
    spec. Returns a list of (data, valid) pairs aligned to ORIGINAL row
    positions (garbage at unselected rows — caller keeps its sel mask).
    """
    from trino_tpu.ops.sort import packed_perm

    n = sel.shape[0]
    ops: list[jnp.ndarray] = [~sel]
    for i, (data, valid) in enumerate(partition_keys):
        ops.extend(sortable_key(data, valid, SortKey(), partition_ranks[i]))
    for i, ((data, valid), sk) in enumerate(zip(order_keys, order_specs)):
        ops.extend(sortable_key(data, valid, sk, order_ranks[i]))
    idx = jnp.arange(n, dtype=jnp.int32)
    perm = packed_perm(ops, n)
    s_sel = sel[perm]

    # partition boundaries (NULLs equal inside a partition key)
    seg_start = jnp.concatenate(
        [jnp.ones(1, dtype=jnp.bool_), s_sel[1:] != s_sel[:-1]]
    )
    for data, valid in partition_keys:
        seg_start = seg_start | _ne_prev(data[perm], valid[perm])
    # peer boundaries (partition boundary or any order key changes)
    peer_start = seg_start
    for data, valid in order_keys:
        peer_start = peer_start | _ne_prev(data[perm], valid[perm])

    seg_first = _running_max_idx(seg_start, n)
    row_number = idx - seg_first + 1

    results: list[tuple[jnp.ndarray, jnp.ndarray]] = []
    peer_last = None
    seg_sizes = None

    def get_peer_last():
        nonlocal peer_last
        if peer_last is None:
            peer_last = jnp.minimum(_next_flag_idx(peer_start, n) - 1, n - 1)
        return peer_last

    def get_seg_sizes():
        nonlocal seg_sizes
        if seg_sizes is None:
            seg_last = jnp.minimum(_next_flag_idx(seg_start, n) - 1, n - 1)
            sizes = row_number[seg_last]  # size of each row's segment
            seg_sizes = sizes
        return seg_sizes

    for fn, arg, dflt in zip(functions, fn_args, fn_defaults):
        if fn.kind == "row_number":
            out = (row_number.astype(jnp.int64), jnp.ones(n, dtype=jnp.bool_))
        elif fn.kind == "rank":
            peer_first = _running_max_idx(peer_start, n)
            out = (
                (peer_first - seg_first + 1).astype(jnp.int64),
                jnp.ones(n, dtype=jnp.bool_),
            )
        elif fn.kind == "percent_rank":
            peer_first = _running_max_idx(peer_start, n)
            rank = (peer_first - seg_first + 1).astype(jnp.float64)
            sizes = get_seg_sizes().astype(jnp.float64)
            out = (
                jnp.where(sizes > 1, (rank - 1) / jnp.maximum(sizes - 1, 1), 0.0),
                jnp.ones(n, dtype=jnp.bool_),
            )
        elif fn.kind == "cume_dist":
            pl = get_peer_last()
            sizes = get_seg_sizes().astype(jnp.float64)
            covered = (pl - seg_first + 1).astype(jnp.float64)
            out = (covered / jnp.maximum(sizes, 1), jnp.ones(n, dtype=jnp.bool_))
        elif fn.kind == "nth_value":
            data, valid = arg
            sd, sv = data[perm], valid[perm]
            if frame.frame == "rows_preceding":
                start = jnp.maximum(seg_first, idx - frame.preceding)
            else:
                start = seg_first
            pos = start + fn.offset - 1
            if frame.frame == "partition":
                seg_last = jnp.minimum(_next_flag_idx(seg_start, n) - 1, n - 1)
                end = seg_last
            elif frame.frame in ("running_rows", "rows_preceding"):
                end = idx
            else:  # running_range: frame extends through the peer group
                end = get_peer_last()
            visible = pos <= end
            posc = jnp.clip(pos, 0, n - 1)
            out = (sd[posc], sv[posc] & visible)
        elif fn.kind == "dense_rank":
            from trino_tpu.ops.aggregation import _prefix_sum
            c = _prefix_sum(peer_start.astype(jnp.int32)).astype(jnp.int64)
            c_at_seg = jax.lax.associative_scan(
                jnp.maximum, jnp.where(seg_start, c, 0)
            )
            out = (c - c_at_seg + 1, jnp.ones(n, dtype=jnp.bool_))
        elif fn.kind == "ntile":
            sizes = get_seg_sizes().astype(jnp.int64)
            k = jnp.int64(fn.offset)
            out = (
                ((row_number.astype(jnp.int64) - 1) * k) // jnp.maximum(sizes, 1) + 1,
                jnp.ones(n, dtype=jnp.bool_),
            )
        elif fn.kind in ("lead", "lag"):
            data, valid = arg
            sd, sv = data[perm], valid[perm]
            off = fn.offset if fn.kind == "lead" else -fn.offset
            j = idx + off
            jc = jnp.clip(j, 0, n - 1)
            in_seg = (seg_first[jc] == seg_first) & (j >= 0) & (j < n) & s_sel[jc]
            cand_d = sd[jc]
            cand_v = sv[jc] & in_seg
            if dflt is not None:
                dd, dv = dflt
                cand_d = jnp.where(in_seg, cand_d, dd[perm])
                cand_v = jnp.where(in_seg, cand_v, dv[perm])
            out = (cand_d, cand_v)
        elif fn.kind == "first_value":
            data, valid = arg
            sd, sv = data[perm], valid[perm]
            if frame.frame == "rows_preceding":
                start = jnp.maximum(seg_first, idx - frame.preceding)
                out = (sd[start], sv[start])
            else:
                out = (sd[seg_first], sv[seg_first])
        elif fn.kind == "last_value":
            data, valid = arg
            sd, sv = data[perm], valid[perm]
            if frame.frame == "partition":
                seg_last = jnp.minimum(_next_flag_idx(seg_start, n) - 1, n - 1)
                out = (sd[seg_last], sv[seg_last])
            elif frame.frame in ("running_rows", "rows_preceding"):
                out = (sd, sv)  # frame ends at the current row
            else:
                pl = get_peer_last()
                out = (sd[pl], sv[pl])
        else:
            # aggregates over the frame
            if fn.kind == "count_star":
                v = s_sel.astype(jnp.int64)
                run = _segmented_scan(v, seg_start, jnp.add)
                out_d, out_v = run, jnp.ones(n, dtype=jnp.bool_)
            else:
                data, valid = arg
                sd = data[perm]
                sv = valid[perm] & s_sel
                if fn.kind == "count":
                    run = _segmented_scan(sv.astype(jnp.int64), seg_start, jnp.add)
                    out_d, out_v = run, jnp.ones(n, dtype=jnp.bool_)
                elif fn.kind in ("sum", "avg"):
                    acc_dtype = (
                        sd.dtype
                        if jnp.issubdtype(sd.dtype, jnp.floating)
                        else jnp.int64
                    )
                    vals = jnp.where(sv, sd, 0).astype(acc_dtype)
                    rs = _segmented_scan(vals, seg_start, jnp.add)
                    rc = _segmented_scan(sv.astype(jnp.int64), seg_start, jnp.add)
                    if fn.kind == "sum":
                        out_d, out_v = rs, rc > 0
                    else:
                        safe = jnp.maximum(rc, 1)
                        if jnp.issubdtype(sd.dtype, jnp.floating):
                            out_d = rs / safe
                        else:
                            # decimal avg: round half up at argument scale
                            out_d = jnp.where(
                                rs >= 0,
                                (rs + safe // 2) // safe,
                                -((-rs + safe // 2) // safe),
                            )
                        out_v = rc > 0
                else:  # min / max
                    big = jnp.asarray(
                        jnp.finfo(sd.dtype).max
                        if jnp.issubdtype(sd.dtype, jnp.floating)
                        else jnp.iinfo(sd.dtype).max,
                        dtype=sd.dtype,
                    )
                    if fn.kind == "min":
                        vals = jnp.where(sv, sd, big)
                        run = _segmented_scan(vals, seg_start, jnp.minimum)
                    else:
                        vals = jnp.where(sv, sd, -big - (0 if jnp.issubdtype(sd.dtype, jnp.floating) else 1))
                        run = _segmented_scan(vals, seg_start, jnp.maximum)
                    rc = _segmented_scan(sv.astype(jnp.int64), seg_start, jnp.add)
                    out_d, out_v = run, rc > 0
            # frame adjustment: whole-partition totals or peer-extended
            if frame.frame == "partition":
                seg_last = jnp.minimum(_next_flag_idx(seg_start, n) - 1, n - 1)
                out_d, out_v = out_d[seg_last], out_v[seg_last]
            elif frame.frame == "running_range":
                pl = get_peer_last()
                out_d, out_v = out_d[pl], out_v[pl]
            elif frame.frame == "rows_preceding":
                out_d, out_v = _rows_preceding_agg(
                    fn, arg, perm, s_sel, seg_first, idx, frame.preceding, n
                )
            out = (out_d, out_v)

        # scatter back to original positions
        od, ov = out
        results.append(
            (
                jnp.zeros_like(od).at[perm].set(od),
                jnp.zeros_like(ov).at[perm].set(ov),
            )
        )
    return results


def _rows_preceding_agg(fn, arg, perm, s_sel, seg_first, idx, k, n):
    """ROWS BETWEEN k PRECEDING AND CURRENT ROW for sum/avg/count/min/max:
    the k+1-row window clipped at the partition start."""
    if fn.kind == "count_star":
        sv = s_sel
        sd = jnp.ones(n, dtype=jnp.int64)
    else:
        data, valid = arg
        sd = data[perm]
        sv = valid[perm] & s_sel
    if fn.kind in ("min", "max"):
        is_min = fn.kind == "min"
        if jnp.issubdtype(sd.dtype, jnp.floating):
            ident = jnp.asarray(
                jnp.finfo(sd.dtype).max if is_min else -jnp.finfo(sd.dtype).max,
                dtype=sd.dtype,
            )
        else:
            ident = jnp.asarray(
                jnp.iinfo(sd.dtype).max if is_min else jnp.iinfo(sd.dtype).min,
                dtype=sd.dtype,
            )
        acc = jnp.where(sv, sd, ident)
        cnt = sv.astype(jnp.int64)
        op = jnp.minimum if is_min else jnp.maximum
        for s in range(1, k + 1):
            j = idx - s
            ok = j >= seg_first
            jc = jnp.maximum(j, 0)
            acc = op(acc, jnp.where(ok & sv[jc], sd[jc], ident))
            cnt = cnt + jnp.where(ok, sv[jc].astype(jnp.int64), 0)
        return acc, cnt > 0
    # additive kinds via running-sum differences
    acc_dtype = sd.dtype if jnp.issubdtype(sd.dtype, jnp.floating) else jnp.int64
    vals = jnp.where(sv, sd, 0).astype(acc_dtype)
    rs = _segmented_scan(vals, _seg_start_from_first(seg_first, idx), jnp.add)
    rc = _segmented_scan(
        sv.astype(jnp.int64), _seg_start_from_first(seg_first, idx), jnp.add
    )
    j = idx - (k + 1)
    ok = j >= seg_first
    jc = jnp.maximum(j, 0)
    wsum = rs - jnp.where(ok, rs[jc], 0)
    wcnt = rc - jnp.where(ok, rc[jc], 0)
    if fn.kind in ("count", "count_star"):
        return wcnt if fn.kind == "count" else wsum, jnp.ones(n, dtype=jnp.bool_)
    if fn.kind == "sum":
        return wsum, wcnt > 0
    # avg
    safe = jnp.maximum(wcnt, 1)
    if jnp.issubdtype(sd.dtype, jnp.floating):
        return wsum / safe, wcnt > 0
    out = jnp.where(
        wsum >= 0, (wsum + safe // 2) // safe, -((-wsum + safe // 2) // safe)
    )
    return out, wcnt > 0


def _seg_start_from_first(seg_first, idx):
    return idx == seg_first
