"""Bit-packing of multi-column sort keys into minimal integer lanes.

XLA:TPU's ``lax.sort`` compile time grows roughly linearly with the
number of sort operands (~4s per int32 lane, ~12s per int64 lane at 2^20
on v5e, doubled again by ``is_stable``) — a sort carrying one bool
selection lane, per-key validity lanes, key lanes and payload lanes
compiles for minutes.  The reference engine has no analogous constraint
(its comparator chains are virtual calls — ``PagesIndex``/
``OrderingCompiler.java``), so this packing tier is pure TPU design:

- every bool/validity/int key is turned into an order-preserving
  unsigned bit-field (ints are offset-binary: ``x XOR signbit``);
- fields are concatenated MSB-first into 63-bit int64 lanes (31-bit
  int32 when everything fits) so ONE unstable single-lane sort realizes
  the full lexicographic multi-key order;
- payload columns RIDE the sort (a post-sort random gather costs ~35ms
  per column at 2^21 rows on v5e — more than the narrow sort itself);
  only the group-key OUTPUTS are recovered by G-sized bit extraction
  from the packed lanes (:class:`KeyPlan`).

Values must already be in *storage* form (int64 bigints, int32 dates,
dictionary codes, bool). Floats cannot be packed (no f64 bitcast under
TPU x64 rewriting) and stay native lanes.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

_LANE_BITS = 63  # int64 lanes, MSB kept zero so signed order == unsigned
_LANE32_BITS = 31


@dataclasses.dataclass(frozen=True)
class Field:
    """One order-preserving unsigned bit-field (value < 2**nbits)."""

    bits: jnp.ndarray  # uint64 (or uint32 when nbits <= 31)
    nbits: int


def bool_field(b: jnp.ndarray) -> Field:
    """False sorts before True."""
    return Field(b.astype(jnp.uint32), 1)


def int_field(x: jnp.ndarray, nbits: int | None = None) -> Field:
    """Signed/unsigned integer -> offset-binary unsigned field.

    ``nbits`` narrows the field when the value range is known (e.g.
    dictionary codes in [−1, len) fit in ``len.bit_length()+1`` bits —
    the +1 covering the −1 null/miss code after biasing).
    """
    w = np.dtype(x.dtype).itemsize * 8
    if x.dtype == jnp.bool_:
        return bool_field(x)
    if nbits is None or nbits >= w:
        if w <= 31:
            # bias to non-negative: offset binary preserves signed order
            return Field((x.astype(jnp.int64) + (1 << (w - 1))).astype(jnp.uint32), w)
        if w == 64:
            return Field(
                (x.astype(jnp.int64) ^ jnp.int64(-(1 << 63))).astype(jnp.uint64), 64
            )
        return Field((x.astype(jnp.int64) + (1 << (w - 1))).astype(jnp.uint64), w)
    # narrowed: bias by 2^(nbits-1) so negatives (e.g. -1 codes) still order
    u = (x.astype(jnp.int64) + (1 << (nbits - 1))).astype(
        jnp.uint32 if nbits <= 31 else jnp.uint64
    )
    return Field(u, nbits)


def masked(f: Field, valid: jnp.ndarray) -> Field:
    """Zero the field on invalid rows (canonical null bits)."""
    return Field(jnp.where(valid, f.bits, jnp.zeros_like(f.bits)), f.nbits)


def pack(fields: Sequence[Field]) -> list[jnp.ndarray]:
    """Concatenate fields MSB-first into sortable integer lanes.

    Returns a list of arrays (int32 single lane when total bits <= 31,
    else int64 lanes); sorting the lanes lexicographically ascending ==
    sorting the original field tuple lexicographically ascending.
    """
    total = sum(f.nbits for f in fields)
    if total <= _LANE32_BITS:
        lane = None
        used = 0
        for f in fields:
            b = f.bits.astype(jnp.uint32)
            lane = b if lane is None else (lane << f.nbits) | b
            used += f.nbits
        return [lane.astype(jnp.int32)]
    lanes: list = []
    cur = None
    rem = _LANE_BITS
    for f in fields:
        bits = f.bits.astype(jnp.uint64)
        nb = f.nbits
        while nb > 0:
            if cur is None:
                cur = jnp.zeros(bits.shape, jnp.uint64)
                rem = _LANE_BITS
            take = min(rem, nb)
            part = (bits >> (nb - take)) if nb > take else bits
            if take < 64:
                part = part & jnp.uint64((1 << take) - 1)
            cur = (cur << take) | part
            rem -= take
            nb -= take
            if rem == 0:
                lanes.append(cur)
                cur = None
    if cur is not None:
        lanes.append(cur << rem)  # left-align the tail lane
    return [ln.astype(jnp.int64) for ln in lanes]


def sort_permutation(
    fields: Sequence[Field], n: int, extra_payload: Sequence[jnp.ndarray] = ()
):
    """Sort rows by ``fields`` (lexicographic, ascending, deterministic:
    ties broken by row index) and return ``(sorted_lanes, perm)`` where
    ``perm`` is the permutation (int32) and ``sorted_lanes`` are the
    packed key lanes in sorted order (WITHOUT the index field).

    ``extra_payload`` lanes ride the sort unmodified (for callers whose
    payload is cheaper to move than to gather).

    Also returns ``first_bit``: the sorted first field's bit per row
    (the ``~sel`` lane when fields came from :func:`key_fields`) — free
    to read from the packed lane, where a ``sel[perm]`` gather would
    cost as much as the sort itself.
    """
    idx_bits = max(1, (n - 1).bit_length())
    iota = jax.lax.iota(jnp.uint32, n)
    base = sum(f.nbits for f in fields)
    all_fields = list(fields)
    if base + idx_bits > _LANE32_BITS:
        # keep the index field inside ONE 63-bit lane: a straddling index
        # could not be extracted (or cleared) with simple shifts
        rem = base % _LANE_BITS
        if rem + idx_bits > _LANE_BITS:
            filler = _LANE_BITS - rem
            all_fields.append(Field(jnp.zeros(n, jnp.uint32), filler))
            base += filler
    all_fields.append(Field(iota, idx_bits))
    total = base + idx_bits
    lanes = pack(all_fields)
    if total <= _LANE32_BITS:
        tail_pad = 0
    else:
        rem = total % _LANE_BITS
        tail_pad = 0 if rem == 0 else _LANE_BITS - rem
    out = jax.lax.sort(
        tuple(lanes) + tuple(extra_payload),
        num_keys=len(lanes),
        is_stable=False,
    )
    s_lanes = list(out[: len(lanes)])
    last = s_lanes[-1]
    if last.dtype == jnp.int32:
        perm = (last.astype(jnp.uint32) & jnp.uint32((1 << idx_bits) - 1)).astype(
            jnp.int32
        )
        cleared = last & jnp.int32(~((1 << idx_bits) - 1))
        top = total - 1
        first_bit = ((s_lanes[0] >> top) & 1).astype(jnp.bool_)
    else:
        u = last.astype(jnp.uint64) >> jnp.uint64(tail_pad)
        perm = (u & jnp.uint64((1 << idx_bits) - 1)).astype(jnp.int32)
        cleared = last & jnp.int64(~(((1 << idx_bits) - 1) << tail_pad))
        first_bit = ((s_lanes[0] >> (_LANE_BITS - 1)) & 1).astype(jnp.bool_)
    # returned key lanes have the index-tiebreak bits zeroed, so equality
    # between adjacent sorted rows means "all key fields equal"
    s_lanes[-1] = cleared
    return s_lanes, perm, list(out[len(lanes):]), first_bit


def key_fields(
    keys: Sequence[tuple[jnp.ndarray, jnp.ndarray | None]],
    sel: jnp.ndarray | None,
) -> tuple[list[Field], list[jnp.ndarray]]:
    """Standard grouping-key field list: selection first (selected rows
    sort to the front), then per key (null-first bit, value bits); wide
    DECIMAL (n,2) keys contribute 128 value bits.  Mirrors the operand
    discipline of the old ``_sortable_keys`` with ~6x fewer sort lanes.

    Returns ``(fields, native_lanes)``: float columns cannot be packed
    (no f64 bitcast under TPU x64 rewriting) and come back as separate
    native sort operands (null-masked to 0)."""
    # the field ORDER and widths are owned by KeyPlan (single layout
    # authority): building from fields_meta keeps pack()'s lane layout and
    # KeyPlan.segments in agreement by construction
    plan = KeyPlan(keys, sel_present=sel is not None)
    return plan.build_fields(keys, sel)


class KeyPlan:
    """Static packing plan for a grouping-key tuple: remembers which bits
    of which lane hold each field, so group-key values can be recovered
    from packed lanes gathered at G segment-start positions (G-sized
    bit ops instead of full-length payload gathers)."""

    def __init__(self, keys, sel_present: bool):
        self.sel_present = sel_present
        self.fields_meta: list = []  # ('sel',)|('valid',ki)|('data',ki,lane,nbits,dtype)
        widths: list[int] = []
        if sel_present:
            self.fields_meta.append(("sel",))
            widths.append(1)
        for ki, (data, valid) in enumerate(keys):
            if valid is not None:
                self.fields_meta.append(("valid", ki))
                widths.append(1)
            if getattr(data, "ndim", 1) == 2:
                for lane in range(2):
                    self.fields_meta.append(("data", ki, lane, 64, data.dtype))
                    widths.append(64)
            elif np.issubdtype(np.dtype(data.dtype), np.floating):
                self.fields_meta.append(("native", ki))
                widths.append(0)  # separate operand, no bits
            else:
                w = 1 if data.dtype == jnp.bool_ else np.dtype(data.dtype).itemsize * 8
                self.fields_meta.append(("data", ki, 0, w, data.dtype))
                widths.append(w)
        total = sum(widths)
        self.lane32 = total <= _LANE32_BITS
        lane_bits = _LANE32_BITS if self.lane32 else _LANE_BITS
        # bit positions (MSB-first walk, matching pack())
        self.segments: list[list[tuple[int, int, int]]] = []  # per field: (lane, shift, nbits)
        pos = 0
        for w in widths:
            segs = []
            rem = w
            while rem > 0:
                lane = pos // lane_bits
                used = pos % lane_bits
                avail = lane_bits - used
                take = min(avail, rem)
                segs.append((lane, used, take))
                pos += take
                rem -= take
            self.segments.append(segs)
        self.num_lanes = (pos + lane_bits - 1) // lane_bits if pos else (1 if total else 0)
        self.lane_bits = lane_bits
        self.total_bits = pos
        # int32 single lane is RIGHT-aligned (pack() shifts as it fills);
        # int64 lanes are full except the LAST, which is LEFT-aligned
        self.tail_pad = 0 if self.lane32 else (lane_bits - (pos % lane_bits)) % lane_bits

    def build_fields(self, keys, sel):
        """Materialize the Field list (and native float lanes) in the
        exact order recorded by ``fields_meta`` — the one walk that both
        ``pack()`` and ``segments`` describe."""
        fields: list[Field] = []
        native: list[jnp.ndarray] = []
        for m in self.fields_meta:
            if m[0] == "sel":
                fields.append(bool_field(~sel))
            elif m[0] == "valid":
                fields.append(bool_field(~keys[m[1]][1]))
            elif m[0] == "native":
                data, valid = keys[m[1]]
                native.append(
                    data if valid is None
                    else jnp.where(valid, data, jnp.zeros_like(data))
                )
            else:
                _, ki, lane, nbits, _dt = m
                data, valid = keys[ki]
                col = data[:, lane] if getattr(data, "ndim", 1) == 2 else data
                f = int_field(col)
                fields.append(f if valid is None else masked(f, valid))
        return fields, native

    def extract(self, lanes: Sequence[jnp.ndarray], field_idx: int) -> jnp.ndarray:
        """Recover a field's unsigned bits from packed lanes (any shape)."""
        segs = self.segments[field_idx]
        total_bits = sum(s[2] for s in segs)
        out = None
        for lane, used, take in segs:
            ln = lanes[lane]
            if self.lane32:
                u = ln.astype(jnp.uint32)
                # right-aligned single lane: field offset counts from the
                # top of the CONTENT (total_bits), not the lane width
                shift = self.total_bits - used - take
            else:
                # the last lane is left-aligned (pack() shifts its tail up),
                # which exactly cancels the missing fill: the piece sits at
                # lane_bits - used - take in EVERY lane
                u = ln.astype(jnp.uint64)
                shift = self.lane_bits - used - take
            piece = (u >> shift) & ((1 << take) - 1)
            if total_bits > 31:
                piece = piece.astype(jnp.uint64)
            out = piece if out is None else ((out << take) | piece)
        return out

    def field_index(self, kind, ki=None):
        for i, m in enumerate(self.fields_meta):
            if m[0] == kind and (ki is None or (len(m) > 1 and m[1] == ki)):
                return i
        return None

    def sel_bit(self, lane0: jnp.ndarray) -> jnp.ndarray:
        """True where the row is SELECTED (the packed field is ~sel)."""
        bit = self.extract(lanes=[lane0] + [lane0] * (self.num_lanes - 1), field_idx=0)
        return bit == 0

    def key_output(self, keys, lanes_at, native_at, ki: int):
        """(data, valid) for key ki recovered at gathered positions."""
        data, valid = keys[ki]
        vi = self.field_index("valid", ki)
        kv = None if vi is None else (self.extract(lanes_at, vi) == 0)
        if getattr(data, "ndim", 1) == 2:
            lanes2 = []
            for lane in range(2):
                fi = self._data_field(ki, lane)
                bits = self.extract(lanes_at, fi).astype(jnp.uint64)
                lanes2.append(
                    (bits ^ jnp.uint64(1 << 63)).astype(jnp.int64)
                )
            return jnp.stack(lanes2, axis=1).astype(data.dtype), kv
        if np.issubdtype(np.dtype(data.dtype), np.floating):
            g = native_at[self._native_pos(ki)]
            return g, kv
        fi = self._data_field(ki, 0)
        meta = self.fields_meta[fi]
        nbits = meta[3]
        bits = self.extract(lanes_at, fi)
        if data.dtype == jnp.bool_:
            return bits.astype(jnp.bool_), kv
        if nbits == 64:
            val = (bits.astype(jnp.uint64) ^ jnp.uint64(1 << 63)).astype(jnp.int64)
        else:
            val = bits.astype(jnp.int64) - (1 << (nbits - 1))
        return val.astype(data.dtype), kv

    def _data_field(self, ki, lane):
        for i, m in enumerate(self.fields_meta):
            if m[0] == "data" and m[1] == ki and m[2] == lane:
                return i
        raise KeyError((ki, lane))

    def _native_pos(self, ki):
        pos = 0
        for m in self.fields_meta:
            if m[0] == "native":
                if m[1] == ki:
                    return pos
                pos += 1
        raise KeyError(ki)


def grouping_sort(
    keys: Sequence[tuple[jnp.ndarray, jnp.ndarray | None]],
    sel: jnp.ndarray | None,
    n: int,
):
    """Sort rows so equal (sel, keys...) tuples are adjacent, selected
    rows first.  Returns ``(eq_lanes, perm, s_sel)`` where adjacent-row
    equality over ``eq_lanes`` means all keys equal and ``s_sel`` is the
    sorted selection mask, read from the packed lane (no gather).  Float
    keys ride as native operands (their position in the significance
    order doesn't matter for grouping, only adjacency)."""
    fields, native = key_fields(keys, sel)
    if not native:
        s_lanes, perm, _, first_bit = sort_permutation(fields, n)
        return s_lanes, perm, ~first_bit
    lanes = pack(fields) if fields else []
    plan = KeyPlan(keys, sel_present=sel is not None)
    iota = jax.lax.iota(jnp.int32, n)
    ops = tuple(lanes) + tuple(native) + (iota,)
    out = jax.lax.sort(ops, num_keys=len(ops), is_stable=False)
    eq_lanes = list(out[: len(lanes) + len(native)])
    s_sel = plan.sel_bit(eq_lanes[0])
    return eq_lanes, out[-1], s_sel


def compact_front_positions(flags: jnp.ndarray, n: int) -> jnp.ndarray:
    """Positions (ascending) of True ``flags`` compacted to the front —
    one single-lane unstable sort of ``(~flag, index)`` packed together.
    Rows beyond the True count hold junk positions."""
    idx_bits = max(1, (n - 1).bit_length())
    iota = jax.lax.iota(jnp.uint32, n)
    if idx_bits + 1 <= _LANE32_BITS:
        lane = ((~flags).astype(jnp.uint32) << idx_bits) | iota
        s = jax.lax.sort((lane.astype(jnp.int32),), num_keys=1, is_stable=False)[0]
        return (s.astype(jnp.uint32) & jnp.uint32((1 << idx_bits) - 1)).astype(
            jnp.int32
        )
    lane = ((~flags).astype(jnp.uint64) << jnp.uint64(idx_bits)) | iota.astype(
        jnp.uint64
    )
    s = jax.lax.sort((lane.astype(jnp.int64),), num_keys=1, is_stable=False)[0]
    return (s.astype(jnp.uint64) & jnp.uint64((1 << idx_bits) - 1)).astype(jnp.int32)


def inverse_permute_mask(perm: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Scatter-free inverse permutation of a bool mask: returns ``out``
    with ``out[perm[i]] = mask[i]`` via one single-lane sort of
    ``(perm << 1) | mask``."""
    n = perm.shape[0]
    if n < (1 << 30):
        lane = (perm.astype(jnp.int32) << 1) | mask.astype(jnp.int32)
        s = jax.lax.sort((lane,), num_keys=1, is_stable=False)[0]
        return (s & 1).astype(jnp.bool_)
    lane = (perm.astype(jnp.int64) << 1) | mask.astype(jnp.int64)
    s = jax.lax.sort((lane,), num_keys=1, is_stable=False)[0]
    return (s & 1).astype(jnp.bool_)
