"""Heavy-hitter (skew) detection: on-device top-k frequency sketch.

A skewed join key (Zipf customers, hot dates) makes ``hash_repartition``
size every (src,dst) block for the hottest destination and overflow-retry
its way up — the cliff described for hash joins in "Design Trade-offs for
a Robust Dynamic Hybrid Hash Join" (arxiv 2112.02480). The TPU/SPMD
translation here: a cheap, static-shape sketch run *inside* the existing
shard_map programs finds keys hot enough to threaten a per-destination
bucket, so the exchange can route them on a separate path
(``parallel/exchange.py::skewed_repartition``).

Sketch: each shard sorts its live key hashes, takes its local top-k
distinct keys by run length, all_gathers the n*k candidates, and psums
exact global counts for every candidate. A key is *hot* when its global
count exceeds ``threshold_frac`` of the per-shard fair share
(``total_rows / n_shards``). The sketch can miss a key only when it is
outside the top-k of every shard; such a key simply stays on the cold
path (and is caught by the spill tier), so misses cost padding, never
correctness.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as PS

from trino_tpu.parallel.mesh import AXIS, smap

# int64 max marks dead rows / empty candidate slots; it sorts last and is
# excluded from hotness explicitly (dead rows would otherwise form a run)
_SENTINEL = jnp.iinfo(jnp.int64).max


def hot_key_sketch(khash, sel, k: int, threshold_frac: float, axis: str = AXIS):
    """Per-shard kernel — call inside a shard_map over ``axis``.

    Args:
      khash: local [m] int64 key hashes (``ops.join.hash_keys`` lane).
      sel: local [m] bool liveness.
      k: candidates kept per shard (static).
      threshold_frac: hot iff global count > frac * total_live / n_shards.

    Returns ``(hot_hashes, hot_valid, n_hot, total_live)``: a sorted
    candidate table of static shape [n*k] replicated across shards (dupes
    and cold/empty slots masked by ``hot_valid``), the hot-key count, and
    the global live-row count (both int64 scalars).
    """
    m = khash.shape[0]
    n = jax.lax.psum(1, axis)
    skey = jax.lax.sort(
        (jnp.where(sel, khash, _SENTINEL),), num_keys=1, is_stable=False
    )[0]
    pos = jnp.arange(m, dtype=jnp.int32)
    left = jnp.searchsorted(skey, skey, side="left").astype(jnp.int32)
    right = jnp.searchsorted(skey, skey, side="right").astype(jnp.int32)
    # one candidate per distinct key: its first occurrence carries the run
    # length; everything else competes with count 0
    cand_count = jnp.where((pos == left) & (skey != _SENTINEL), right - left, 0)
    neg_sorted, cand = jax.lax.sort((-cand_count, skey), num_keys=1, is_stable=False)
    kk = min(k, m)
    top = jnp.where(-neg_sorted[:kk] > 0, cand[:kk], _SENTINEL)
    if kk < k:
        top = jnp.concatenate([top, jnp.full((k - kk,), _SENTINEL, dtype=jnp.int64)])
    gcand = jax.lax.all_gather(top, axis, axis=0, tiled=True)  # [n*k]
    # exact global count for every candidate (psum of local run lengths)
    lo = jnp.searchsorted(skey, gcand, side="left")
    hi = jnp.searchsorted(skey, gcand, side="right")
    gcount = jax.lax.psum((hi - lo).astype(jnp.int64), axis)
    total = jax.lax.psum(jnp.sum(sel.astype(jnp.int64)), axis)
    hot = (gcount.astype(jnp.float64) * n > threshold_frac * total.astype(jnp.float64))
    hot = hot & (gcand != _SENTINEL)
    # sort candidates by hash for searchsorted membership; duplicates of a
    # hash share one global count (and thus one hot flag), so keeping only
    # the first occurrence loses nothing
    sh, hflag = jax.lax.sort((gcand, hot.astype(jnp.int32)), num_keys=1, is_stable=False)
    first = jnp.arange(sh.shape[0], dtype=jnp.int32) == jnp.searchsorted(
        sh, sh, side="left"
    ).astype(jnp.int32)
    hvalid = first & (hflag > 0)
    n_hot = jnp.sum(hvalid.astype(jnp.int64))
    return sh, hvalid, n_hot, total


def is_hot(hot_hashes, hot_valid, khash):
    """Membership of each ``khash`` row in the sketch's hot set.

    ``hot_hashes`` must be the sorted table from ``hot_key_sketch`` (first
    occurrence of each hash carries validity).
    """
    idx = jnp.searchsorted(hot_hashes, khash, side="left")
    idx = jnp.minimum(idx, hot_hashes.shape[0] - 1).astype(jnp.int32)
    return (hot_hashes[idx] == khash) & hot_valid[idx]


def hot_key_hashes(mesh: Mesh, key_hash, sel, k: int, threshold_frac: float):
    """Eager mesh-level wrapper (interpreter path): sketch over global
    row-sharded ``key_hash``/``sel``. Returns replicated
    ``(hot_hashes, hot_valid, n_hot, total_live)``."""

    @partial(
        smap,
        mesh=mesh,
        in_specs=(PS(AXIS), PS(AXIS)),
        out_specs=(PS(), PS(), PS(), PS()),
    )
    def go(khash, s):
        return hot_key_sketch(khash, s, k, threshold_frac)

    return go(key_hash, sel)
