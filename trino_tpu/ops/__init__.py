"""Device kernels: filter/project, aggregation, sort, topN, join.

These are the TPU-native equivalents of Trino's hot operators
(``core/trino-main/src/main/java/io/trino/operator/``): pure functions over
fixed-shape arrays, designed to be jit-compiled and XLA-fused, using
sort/segment-reduce formulations instead of scatter-heavy hash tables.
"""
