"""ORDER BY / TopN kernels.

Reference: ``operator/OrderByOperator.java:45`` (PagesIndex sort),
``operator/TopNOperator.java:37``.

TPU-first: multi-key lexicographic ``lax.sort`` over bit-transformed keys.
Each key column is mapped to an unsigned-comparable integer form so that a
single ascending sort realizes asc/desc and nulls-first/last:

- integers: value (negated bitwise for desc)
- floats: IEEE-754 total-order trick (flip sign bit or all bits)
- strings: dictionary rank (host precomputed)
- nulls: a separate leading key per column encodes null position
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class SortKey:
    ascending: bool = True
    nulls_first: bool = False  # Trino default: NULLS LAST for ASC


def sortable_key(data: jnp.ndarray, valid: jnp.ndarray, key: SortKey, ranks=None):
    """Return list of sort operand arrays for one key column (null key +
    value key), already oriented for ascending lax.sort.

    Floats are sorted as native float operands (lax.sort has a total order);
    descending = negation. No bitcasts — f64 bitcast is unsupported under
    TPU's x64 rewriting.
    """
    if getattr(data, "ndim", 1) == 2:
        # wide DECIMAL (hi, lo) lanes: two-operand signed-128 ordering
        from trino_tpu.ops.decimal128 import sort_operands_wide

        ops = sort_operands_wide(data[:, 0], data[:, 1], key.ascending)
        null_key = valid if key.nulls_first else ~valid
        ops = [jnp.where(valid, o, jnp.zeros_like(o)) for o in ops]
        return [null_key] + ops
    if ranks is not None:  # dictionary string: map codes to ranks
        r = jnp.asarray(ranks)
        if r.shape[0] == 0:
            # empty dictionary: only padding rows (valid False) exist
            value = jnp.zeros(data.shape[0], dtype=jnp.int64)
        else:
            value = r[jnp.maximum(data, 0)].astype(jnp.int64)
        if not key.ascending:
            value = -1 - value
    elif np.issubdtype(np.dtype(data.dtype), np.floating):
        value = data if key.ascending else -data
    elif data.dtype == jnp.bool_:
        value = data.astype(jnp.int32)
        if not key.ascending:
            value = -value
    else:
        value = data.astype(jnp.int64)
        if not key.ascending:
            value = -1 - value  # bitwise complement keeps total order reversed
    # null ordering: nulls_first -> null key False sorts first for nulls
    null_key = valid if key.nulls_first else ~valid
    value = jnp.where(valid, value, jnp.zeros_like(value))
    return [null_key, value]


def sort_indices(
    key_arrays: Sequence[tuple[jnp.ndarray, jnp.ndarray]],
    keys: Sequence[SortKey],
    sel: jnp.ndarray,
    ranks_per_key: Sequence[np.ndarray | None] | None = None,
) -> jnp.ndarray:
    """Return permutation putting selected rows first in key order
    (deterministic: ties broken by original row index).

    Integer/dictionary/bool keys are bit-packed into 1-2 sort lanes
    (ops/keypack.py — XLA:TPU sort compile time is ~linear in operand
    count); float keys stay native operands in their significance slot.
    """
    n = sel.shape[0]
    ops: list = [~sel]
    for i, ((data, valid), k) in enumerate(zip(key_arrays, keys)):
        ranks = ranks_per_key[i] if ranks_per_key else None
        ops.extend(sortable_key(data, valid, k, ranks))
    return packed_perm(ops, n)


def packed_perm(oriented_ops: Sequence[jnp.ndarray], n: int) -> jnp.ndarray:
    """Sort permutation over pre-oriented operand arrays (ascending
    lexicographic, deterministic via row-index tiebreak), with runs of
    bool/int operands bit-packed into minimal integer lanes and float
    operands kept native in their significance slot."""
    from trino_tpu.ops import keypack as KP

    runs: list = []  # ('f', [Field...]) | ('n', lane) in significance order

    def add_field(f):
        if runs and runs[-1][0] == "f":
            runs[-1][1].append(f)
        else:
            runs.append(("f", [f]))

    for op in oriented_ops:
        if np.issubdtype(np.dtype(op.dtype), np.floating):
            runs.append(("n", op))
        elif op.dtype == jnp.bool_:
            add_field(KP.bool_field(op))
        else:
            add_field(KP.int_field(op))
    if len(runs) == 1 and runs[0][0] == "f":
        _, perm, _, _ = KP.sort_permutation(runs[0][1], n)
        return perm
    lanes: list = []
    for kind, payload in runs:
        if kind == "f":
            lanes.extend(KP.pack(payload))
        else:
            lanes.append(payload)
    idx = jnp.arange(n, dtype=jnp.int32)
    out = jax.lax.sort(
        tuple(lanes) + (idx,), num_keys=len(lanes) + 1, is_stable=False
    )
    return out[-1]
