"""ORDER BY / TopN kernels.

Reference: ``operator/OrderByOperator.java:45`` (PagesIndex sort),
``operator/TopNOperator.java:37``.

TPU-first: multi-key lexicographic ``lax.sort`` over bit-transformed keys.
Each key column is mapped to an unsigned-comparable integer form so that a
single ascending sort realizes asc/desc and nulls-first/last:

- integers: value (negated bitwise for desc)
- floats: IEEE-754 total-order trick (flip sign bit or all bits)
- strings: dictionary rank (host precomputed)
- nulls: a separate leading key per column encodes null position
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class SortKey:
    ascending: bool = True
    nulls_first: bool = False  # Trino default: NULLS LAST for ASC


def sortable_key(data: jnp.ndarray, valid: jnp.ndarray, key: SortKey, ranks=None):
    """Return list of sort operand arrays for one key column (null key +
    value key), already oriented for ascending lax.sort.

    Floats are sorted as native float operands (lax.sort has a total order);
    descending = negation. No bitcasts — f64 bitcast is unsupported under
    TPU's x64 rewriting.
    """
    if getattr(data, "ndim", 1) == 2:
        # wide DECIMAL (hi, lo) lanes: two-operand signed-128 ordering
        from trino_tpu.ops.decimal128 import sort_operands_wide

        ops = sort_operands_wide(data[:, 0], data[:, 1], key.ascending)
        null_key = valid if key.nulls_first else ~valid
        ops = [jnp.where(valid, o, jnp.zeros_like(o)) for o in ops]
        return [null_key] + ops
    if ranks is not None:  # dictionary string: map codes to ranks
        r = jnp.asarray(ranks)
        if r.shape[0] == 0:
            # empty dictionary: only padding rows (valid False) exist
            value = jnp.zeros(data.shape[0], dtype=jnp.int64)
        else:
            value = r[jnp.maximum(data, 0)].astype(jnp.int64)
        if not key.ascending:
            value = -1 - value
    elif np.issubdtype(np.dtype(data.dtype), np.floating):
        value = data if key.ascending else -data
    elif data.dtype == jnp.bool_:
        value = data.astype(jnp.int32)
        if not key.ascending:
            value = -value
    else:
        value = data.astype(jnp.int64)
        if not key.ascending:
            value = -1 - value  # bitwise complement keeps total order reversed
    # null ordering: nulls_first -> null key False sorts first for nulls
    null_key = valid if key.nulls_first else ~valid
    value = jnp.where(valid, value, jnp.zeros_like(value))
    return [null_key, value]


def sort_indices(
    key_arrays: Sequence[tuple[jnp.ndarray, jnp.ndarray]],
    keys: Sequence[SortKey],
    sel: jnp.ndarray,
    ranks_per_key: Sequence[np.ndarray | None] | None = None,
) -> jnp.ndarray:
    """Return permutation putting selected rows first in key order."""
    n = sel.shape[0]
    ops = [~sel]
    for i, ((data, valid), k) in enumerate(zip(key_arrays, keys)):
        ranks = ranks_per_key[i] if ranks_per_key else None
        ops.extend(sortable_key(data, valid, k, ranks))
    idx = jnp.arange(n, dtype=jnp.int32)
    out = jax.lax.sort(tuple(ops) + (idx,), num_keys=len(ops), is_stable=True)
    return out[-1]
