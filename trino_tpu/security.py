"""Access control.

Reference: ``core/trino-main/.../security/AccessControlManager.java``
multiplexing system + connector access controls, and the file-based
system access control of ``lib/trino-plugin-toolkit``
(``FileBasedSystemAccessControl``: catalog/schema/table rules with user
regex matching).

The engine consults ``check_can_select`` / ``check_can_insert`` /
``check_can_drop`` before executing; the default control allows all
(reference: ``AllowAllSystemAccessControl``)."""

from __future__ import annotations

import dataclasses
import re
from typing import Optional


class AccessDeniedError(Exception):
    def __init__(self, what: str):
        super().__init__(f"Access Denied: {what}")


@dataclasses.dataclass
class CatalogRule:
    """One rule of a file-based policy: first match wins."""

    user_pattern: str = ".*"
    catalog_pattern: str = ".*"
    allow: str = "all"  # all | read-only | none

    def matches(self, user: str, catalog: str) -> bool:
        return bool(
            re.fullmatch(self.user_pattern, user or "")
            and re.fullmatch(self.catalog_pattern, catalog or "")
        )


class AccessControl:
    """allow-all base (AllowAllSystemAccessControl)."""

    def check_can_select(self, user: str, catalog: str, schema: str, table: str):
        pass

    def check_can_insert(self, user: str, catalog: str, schema: str, table: str):
        pass

    def check_can_create(self, user: str, catalog: str, schema: str, table: str):
        pass

    def check_can_drop(self, user: str, catalog: str, schema: str, table: str):
        pass

    def filter_catalogs(self, user: str, catalogs: list[str]) -> list[str]:
        return catalogs


class FileBasedAccessControl(AccessControl):
    """Rules in the shape of the reference's rules.json:
    {"catalogs": [{"user": "...", "catalog": "...", "allow": "all|read-only|none"}]}
    First matching rule wins; no match denies (reference behavior)."""

    def __init__(self, config: dict):
        self.rules = [
            CatalogRule(
                r.get("user", ".*"), r.get("catalog", ".*"), r.get("allow", "none")
            )
            for r in config.get("catalogs", [])
        ]

    def _allow(self, user: str, catalog: str) -> str:
        for rule in self.rules:
            if rule.matches(user, catalog):
                return rule.allow
        return "none"

    def check_can_select(self, user, catalog, schema, table):
        if self._allow(user, catalog) == "none":
            raise AccessDeniedError(f"Cannot select from {catalog}.{schema}.{table}")

    def check_can_insert(self, user, catalog, schema, table):
        if self._allow(user, catalog) != "all":
            raise AccessDeniedError(f"Cannot insert into {catalog}.{schema}.{table}")

    def check_can_create(self, user, catalog, schema, table):
        if self._allow(user, catalog) != "all":
            raise AccessDeniedError(f"Cannot create {catalog}.{schema}.{table}")

    def check_can_drop(self, user, catalog, schema, table):
        if self._allow(user, catalog) != "all":
            raise AccessDeniedError(f"Cannot drop {catalog}.{schema}.{table}")

    def filter_catalogs(self, user, catalogs):
        return [c for c in catalogs if self._allow(user, c) != "none"]


class AccessControlManager(AccessControl):
    """Chains system access controls; every control must allow
    (AccessControlManager semantics)."""

    def __init__(self):
        self._controls: list[AccessControl] = []
        # bumped whenever policy changes; cached query plans embed the
        # generation so a policy change invalidates plan-time checks
        self.generation = 0

    def add(self, control: AccessControl) -> None:
        self._controls.append(control)
        self.generation += 1

    def check_can_select(self, user, catalog, schema, table):
        for c in self._controls:
            c.check_can_select(user, catalog, schema, table)

    def check_can_insert(self, user, catalog, schema, table):
        for c in self._controls:
            c.check_can_insert(user, catalog, schema, table)

    def check_can_create(self, user, catalog, schema, table):
        for c in self._controls:
            c.check_can_create(user, catalog, schema, table)

    def check_can_drop(self, user, catalog, schema, table):
        for c in self._controls:
            c.check_can_drop(user, catalog, schema, table)

    def filter_catalogs(self, user, catalogs):
        for c in self._controls:
            catalogs = c.filter_catalogs(user, catalogs)
        return catalogs
