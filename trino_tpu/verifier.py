"""Query verifier: replay a query corpus against two engines and diff.

Reference: ``service/trino-verifier`` — replays logged queries against a
control and a test cluster and reports result mismatches. Here the
control/test pair is any two of: a server URI (``http://...``), ``local``,
or ``distributed`` — e.g. verifying the mesh-SPMD executor against the
single-chip executor, or a new build against a running server.

Usage:
    python -m trino_tpu.verifier --control local --test distributed \
        --queries queries.sql [--max-rows 100000]
Each statement in the file (``;``-separated) runs on both; rows are
compared as sorted multisets.
"""

from __future__ import annotations

import argparse
import sys
import time
from decimal import Decimal
from typing import Callable


def _runner_for(spec: str) -> Callable[[str], list[tuple]]:
    if spec.startswith("http://") or spec.startswith("https://"):
        from trino_tpu.client import Connection

        conn = Connection(spec)
        return lambda sql: conn.execute(sql)[0]
    if spec == "local":
        from trino_tpu.testing import LocalQueryRunner

        r = LocalQueryRunner()
        return lambda sql: r.execute(sql)[0]
    if spec == "distributed":
        from trino_tpu.testing import DistributedQueryRunner

        r = DistributedQueryRunner()
        return lambda sql: r.execute(sql)[0]
    raise ValueError(f"unknown engine spec: {spec}")


def _normalize(rows: list[tuple]) -> list[tuple]:
    out = []
    for row in rows:
        out.append(
            tuple(
                float(v) if isinstance(v, Decimal) else v
                for v in row
            )
        )
    return sorted(out, key=repr)


def verify(
    control: str, test: str, queries: list[str], max_rows: int = 1_000_000
) -> int:
    """Returns the number of mismatching queries (0 = success)."""
    run_c = _runner_for(control)
    run_t = _runner_for(test)
    failures = 0
    for i, sql in enumerate(queries):
        sql = sql.strip()
        if not sql:
            continue
        label = f"[{i + 1}/{len(queries)}]"
        try:
            t0 = time.time()
            rc = run_c(sql)
            tc = time.time() - t0
            t0 = time.time()
            rt = run_t(sql)
            tt = time.time() - t0
        except Exception as e:  # noqa: BLE001 — report and continue
            print(f"{label} ERROR: {e}\n  {sql[:120]}")
            failures += 1
            continue
        if len(rc) > max_rows or len(rt) > max_rows:
            print(f"{label} SKIP (too many rows): {sql[:80]}")
            continue
        nc, nt = _normalize(rc), _normalize(rt)
        if nc == nt:
            print(f"{label} OK   {len(rc):7d} rows  control {tc:5.2f}s test {tt:5.2f}s")
        else:
            failures += 1
            print(f"{label} MISMATCH ({len(nc)} vs {len(nt)} rows): {sql[:100]}")
            for a, b in list(zip(nc, nt))[:3]:
                if a != b:
                    print(f"    control: {a}\n    test:    {b}")
                    break
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trino-tpu-verifier")
    ap.add_argument("--control", required=True, help="http://..., local, distributed")
    ap.add_argument("--test", required=True)
    ap.add_argument("--queries", required=True, help="file of ;-separated SQL")
    ap.add_argument("--max-rows", type=int, default=1_000_000)
    args = ap.parse_args(argv)
    with open(args.queries) as f:
        queries = [q for q in f.read().split(";") if q.strip()]
    failures = verify(args.control, args.test, queries, args.max_rows)
    print(f"{len(queries)} queries, {failures} failures")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
