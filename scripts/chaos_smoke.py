"""Chaos smoke: one TPC-H query under 30% task-crash injection.

Boots a 2-worker cluster, runs TPC-H Q1 twice — fault-free, then with
``fault_task_crash_p=0.3`` + ``retry_policy=TASK`` — and checks the
results are bit-identical and that at least one task retry happened.
Quick manual repro for the fault-tolerance stack (CI runs the same
scenario as ``tests/test_fault_tolerance.py -m faults``).

Usage: JAX_PLATFORMS=cpu python scripts/chaos_smoke.py [seed]
"""

import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from trino_tpu.testing import MultiProcessQueryRunner

Q1 = """select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
              sum(l_extendedprice) as sum_base_price,
              avg(l_discount) as avg_disc, count(*) as count_order
       from lineitem where l_shipdate <= date '1998-09-02'
       group by l_returnflag, l_linestatus
       order by l_returnflag, l_linestatus"""

# skewed partitioned join: least() collapses ~93% of order rows onto one
# join key, so the heavy-hitter path (ops/skew.py + salted exchange) and
# fault injection are exercised together
Q_SKEW = """select count(*) as c, sum(o.o_totalprice * c.c_custkey) as chk
       from orders o join customer c on least(o.o_custkey, 100) = c.c_custkey"""


def main() -> int:
    # default seed 3: both partitions of Q1's scan fragment draw below
    # 0.3 on attempt 1 and survive on attempt 2 — guaranteed retries
    seed = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    chaos = {
        "retry_policy": "TASK",
        "task_retry_attempts": 8,
        "fault_injection_seed": seed,
        "fault_task_crash_p": 0.3,
        "retry_initial_delay_ms": 20,
        "retry_max_delay_ms": 200,
    }
    skew_props = {"join_distribution_type": "PARTITIONED"}
    # the summary dict is built incrementally and emitted in a finally, so
    # a crash mid-scenario still prints one machine-readable JSON line with
    # whatever was gathered (partial: true)
    summary: dict = {"seed": seed, "partial": True}
    try:
        with MultiProcessQueryRunner(n_workers=2) as runner:
            clean, _ = runner.execute(Q1)
            chaotic, _ = runner.execute(Q1, session_properties=chaos)
            skew_clean, _ = runner.execute(
                Q_SKEW, session_properties=skew_props
            )
            skew_chaotic, _ = runner.execute(
                Q_SKEW, session_properties={**chaos, **skew_props}
            )
            from trino_tpu.server import auth

            req = urllib.request.Request(
                f"{runner.coordinator_uri}/v1/query", headers=auth.headers()
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                queries = json.loads(r.read().decode())
            # coordinator metrics snapshot (task retries/attempt histograms)
            # must be scraped before the cluster shuts down
            with urllib.request.urlopen(
                f"{runner.coordinator_uri}/v1/metrics?format=json", timeout=10
            ) as r:
                summary["metrics"] = json.loads(r.read().decode())
        retries = max(q.get("taskRetries", 0) for q in queries)
        summary.update(
            seed=seed, rows=len(chaotic), task_retries=retries, partial=False
        )
        print(f"seed={seed} rows={len(chaotic)} task_retries={retries}")
        if chaotic != clean:
            print("FAIL: chaotic result differs from fault-free result")
            summary["ok"] = False
            return 1
        if skew_chaotic != skew_clean:
            print("FAIL: skewed-join chaotic result differs from fault-free")
            summary["ok"] = False
            return 1
        if retries == 0:
            print("WARN: no retries at this seed — injection never fired")
        print("OK: bit-identical under 30% task-crash injection (incl. skewed join)")
        summary["ok"] = True
        return 0
    finally:
        print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    sys.exit(main())
