"""Chaos smoke: TPC-H under task-crash injection plus a slow worker.

Boots a 2-worker cluster and runs three scenarios:

1. TPC-H Q1 fault-free vs ``fault_task_crash_p=0.3`` +
   ``retry_policy=TASK`` — results must be bit-identical and at least
   one task retry should fire.
2. A skewed partitioned join under the same crash injection.
3. ``slow-worker``: worker-1 deterministically slowed 10× via
   ``fault_slow_workers`` and ``fault_task_slow_factor`` with
   ``speculation=true`` — the straggler detector must hedge at least
   one attempt onto the healthy worker, results stay bit-identical,
   and the speculative counters land in the summary line.
4. ``concurrent-clients``: N threads fire literal-variant aggregations
   with cross-query batching enabled (``batch_window_ms``>0,
   ``execution_mode=distributed`` so the coordinator's own engine — the
   tier that batches — executes them). Every concurrent result must be
   bit-identical to its sequential run; batched-dispatch counters land
   in the summary line.
5. ``node-death`` (the 2-worker cluster's last scenario — a worker does
   not survive it): with ``retry_policy=TASK`` +
   ``exchange_spooling=true`` (execution pinned per-fragment), the
   worker that ran Q1's scan fragment ``os._exit``s right after that
   task finishes (``fault_worker_exit_site=2.0``; every task stalls 1s
   pre-execute so the partial-agg consumers provably pull AFTER the
   death). Spool recovery must keep the result bit-identical with NO
   query-level retry (queryAttempts == 1); spooled-bytes and
   recovered-task counters land in the summary.
6. ``fused-node-death`` (its own 3-worker cluster): fusion AND spooling
   on together. A join of two grouped subqueries fuses into two units
   feeding a worker-side join stage; the worker that ran the first
   unit's task is SIGKILLed right after it finishes. The stalled join
   consumers pull after the death, so recovery must engage at unit
   granularity — FAIL on row drift, on queryAttempts > 1, or on
   fusedFragments == 0 (the query silently not fusing would void the
   scenario); recovered/spooled/fused counters land in the summary.

7. ``star-join`` (its own 3-worker cluster): a TPC-DS star query whose
   broadcast dimension builds fuse INTO the fact-probe program (the
   dense join tier's multiway fusion) runs once clean, then with the
   worker that executed the fused unit's task SIGKILLed right after the
   task finishes. FAIL on row drift, on a query-level retry
   (queryAttempts > 1), on the query not fusing, or on the dense
   strategy not being the one that ran (exchangeStats.joinStrategy) —
   recovery must engage at unit granularity, same ladder as
   fused-node-death but across a multiway join program.

8. ``adaptive-warmup`` (in-process, no cluster): a Zipf-skewed
   partitioned join with skew handling OFF runs cold, recording
   observed truth (capacities AND the dense-join key domain) into a
   persistent query-history store; a FRESH engine sharing the same
   ``history_dir`` then repeats the query. FAIL unless the warm run
   shows ``overflow_retries == 0`` AND ``compile_halvings == 0`` AND
   bit-identical rows AND the history-driven join promotion: the cold
   run picks the dense tier, the warm run reads the history-seeded key
   domain through the cost gate and promotes the same site to the
   matmul tier (``joinStrategy`` dense -> matmul). When the cold run
   actually grew a site, the warm run must additionally show at least
   one capacity with provenance ``history``.

Quick manual repro for the fault-tolerance stack (CI runs the same
scenarios as ``tests/test_fault_tolerance.py -m faults`` /
``tests/test_speculation.py`` / ``tests/test_spool.py``).

9. ``overload`` (own entry point: ``chaos_smoke.py overload``): an
   in-process coordinator with deliberately tiny admission capacity is
   offered 4× that capacity from closed-loop retrying clients while a
   burst tenant trips the token bucket. FAIL on row drift of any
   ADMITTED query, on a 503 that does not carry Retry-After, or on
   queue depth exceeding the closed-loop bound (unbounded growth means
   abandoned waiters are leaking).

Quick manual repro for the fault-tolerance stack (CI runs the same
scenarios as ``tests/test_fault_tolerance.py -m faults`` /
``tests/test_speculation.py`` / ``tests/test_spool.py``).

10. ``live-append`` (own entry point: ``chaos_smoke.py live-append``):
    reader threads hammer a RESULT-cached aggregation while a writer
    appends a new part to the scanned table mid-storm. Every result a
    reader observes must equal the pre-append snapshot or the
    post-append snapshot — never a torn mix — and the final read must
    show the appended rows (served via incremental maintenance, not a
    cold re-execution; maintained/invalidation counters land in the
    summary line).

11. ``post-mortem`` (rides the fused-node-death cluster): the death
    query journals its lifecycle to an on-disk flight recorder
    (``flight_dir`` → obs/flight.py). After the cluster — coordinator
    included — is torn down, the journal is replayed straight from disk
    and must ALONE explain the recovery: created→completed lifecycle,
    retry attempts and recovered levels matching the live /v1/query
    scrape, final queryStats and operatorStats. FAIL on any missing or
    mismatched piece; the verdict (per-check booleans) lands in the
    summary line under ``post_mortem``.

Usage: JAX_PLATFORMS=cpu python scripts/chaos_smoke.py
       [seed|overload|live-append]
"""

import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from trino_tpu.testing import MultiProcessQueryRunner

Q1 = """select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
              sum(l_extendedprice) as sum_base_price,
              avg(l_discount) as avg_disc, count(*) as count_order
       from lineitem where l_shipdate <= date '1998-09-02'
       group by l_returnflag, l_linestatus
       order by l_returnflag, l_linestatus"""

# skewed partitioned join: least() collapses ~93% of order rows onto one
# join key, so the heavy-hitter path (ops/skew.py + salted exchange) and
# fault injection are exercised together
Q_SKEW = """select count(*) as c, sum(o.o_totalprice * c.c_custkey) as chk
       from orders o join customer c on least(o.o_custkey, 100) = c.c_custkey"""

# literal-variant shape for the concurrent-clients scenario: the four
# threads differ only in the hoisted comparison literal, so their plans
# share one canonical fingerprint and are batchable; ORDER BY pins row
# order (skew handling is off inside a batched dispatch)
Q_BATCH = """select l_returnflag, count(*) as c, sum(l_quantity) as s
       from lineitem where l_quantity < {} group by l_returnflag
       order by l_returnflag"""

# fused-node-death: two grouped subqueries fuse into two pipeline units
# feeding a worker-side join stage (PARTITIONED + fusion_max_fragments=2).
# The join's tasks are stallable, so killing a unit's worker right after
# the unit task finishes is provably observed — recovery must engage at
# unit granularity (spool re-point of the unit's boundary output, or an
# atomic whole-unit re-execution)
Q_FUSED = """select a.k, a.c, b.s from
       (select l_returnflag as k, count(*) as c from lineitem
        group by l_returnflag) a
       join (select l_returnflag as k, sum(l_quantity) as s from lineitem
        group by l_returnflag) b on a.k = b.k order by a.k"""

FUSED_PROPS = {
    "join_distribution_type": "PARTITIONED",
    "fusion_max_fragments": 2,
}

# star-join: fact probes against two broadcast dimension builds — with
# the dense join tier on (default) the dims are absorbed into ONE
# multiway fused program (planner/fragmenter.py broadcast_links), the
# shape the worker-SIGKILL scenario must recover at unit granularity
Q_STAR = """select i.i_category, d.d_year, sum(ss.ss_ext_sales_price) as s
       from tpcds.tiny.store_sales ss
       join tpcds.tiny.item i on ss.ss_item_sk = i.i_item_sk
       join tpcds.tiny.date_dim d on ss.ss_sold_date_sk = d.d_date_sk
       group by i.i_category, d.d_year
       order by i.i_category, d.d_year"""


def _fused_unit_site(sql, **props):
    """Fault site of the first fused unit's task ('{unit_root}.0'),
    computed from the same fuse_groups decision the scheduler makes."""
    from trino_tpu.exec.fragments import fragment_fusable
    from trino_tpu.planner.fragmenter import (
        FusedFragment,
        filtered_broadcast_fids,
        fragment_plan,
        fuse_groups,
        partitioned_join_pairs,
    )
    from trino_tpu.testing import LocalQueryRunner

    r = LocalQueryRunner()
    r.session.set("execution_mode", "distributed")
    for k, v in props.items():
        r.session.set(k, v)
    sub = fragment_plan(r.plan(sql))
    units = [
        u
        for u in fuse_groups(
            sub,
            fusable=fragment_fusable,
            max_fragments=max(1, int(r.session.get("fusion_max_fragments"))),
            skew_pairs=(
                partitioned_join_pairs(sub)
                if bool(r.session.get("skew_handling"))
                else ()
            ),
            include_root=False,
            broadcast_links=bool(r.session.get("dense_join")),
            blocked=(
                frozenset(filtered_broadcast_fids(sub))
                if bool(r.session.get("enable_dynamic_filtering"))
                else frozenset()
            ),
        )
        if isinstance(u, FusedFragment)
    ]
    if not units:
        return None
    return f"{units[0].id}.0"


def _operator_rollup(query_infos) -> dict:
    """Operator row-flow rollup across scraped /v1/query records: total
    rows in/out per operator kind plus the worst (largest out/in)
    partial-agg reduction ratio — the mid-query-adaptivity signal."""
    out: dict = {}
    worst = None
    for q in query_infos:
        for ent in (q.get("operatorStats") or {}).values():
            kind = str(ent.get("kind") or "")
            if not kind:
                continue
            key = kind.replace("-", "_")
            rin = int(ent.get("rows_in", 0) or 0)
            rout = int(ent.get("rows_out", 0) or 0)
            out[f"{key}_rows_in"] = out.get(f"{key}_rows_in", 0) + rin
            out[f"{key}_rows_out"] = out.get(f"{key}_rows_out", 0) + rout
            if kind == "partial-agg" and rin > 0:
                ratio = rout / rin
                worst = ratio if worst is None else max(worst, ratio)
    if worst is not None:
        out["worst_partial_agg_reduction"] = round(worst, 4)
    return out


def _post_mortem_verdict(events: list, live_info: dict) -> dict:
    """Judge whether the flight journal ALONE explains the fused-node-
    death recovery: it must carry the lifecycle (created→completed), the
    retry/recovery accounting matching the live /v1/query scrape, and
    the final stats — a coordinator that died right after this query
    would leave an operator with exactly these bytes."""
    names = [e.get("event") for e in events]
    completed = next(
        (e for e in reversed(events) if e.get("event") == "completed"), {}
    )
    qs = completed.get("queryStats") or {}
    checks = {
        "has_created": "created" in names,
        "has_completed": bool(completed),
        "finished": completed.get("state") == "FINISHED",
        "has_final_stats": bool(qs) and "elapsedMs" in qs,
        "has_operator_stats": bool(completed.get("operatorStats")),
        "attempts_match": (
            completed.get("queryAttempts") == live_info.get("queryAttempts")
        ),
        "recovery_match": (
            int(completed.get("recoveredTasks") or 0)
            == int(live_info.get("recoveredTasks") or 0)
            and (completed.get("recoveredTaskLevels") or {})
            == (live_info.get("recoveredTaskLevels") or {})
        ),
    }
    return {
        "events": names,
        "explains_recovery": all(checks.values()),
        "checks": checks,
        "query_attempts": completed.get("queryAttempts"),
        "recovered_tasks": completed.get("recoveredTasks"),
        "recovered_levels": completed.get("recoveredTaskLevels"),
        "state": completed.get("state"),
    }


def _adaptive_warmup(seed: int) -> dict:
    """Cold overflowing skewed join, then the same query on a FRESH
    engine sharing the persistent history store. The warm engine has no
    in-process program cache or stats for the query — everything it
    knows arrives through ``{history_dir}/query_history.json`` — so a
    clean warm run proves the record → seed feedback loop end to end."""
    import tempfile

    import numpy as np

    from trino_tpu import types as T
    from trino_tpu.columnar import Batch, Column
    from trino_tpu.config import Session
    from trino_tpu.connectors.api import ColumnSchema, TableSchema
    from trino_tpu.testing import LocalQueryRunner

    n = 1 << 16
    sql = ("select sum(f.v * d.name) as chk, count(*) as c "
           "from memory.default.facts f "
           "join memory.default.dims d on f.k = d.k")

    def _seed(catalogs):
        mem = catalogs.get("memory")
        rng = np.random.default_rng(seed)
        raw = rng.zipf(1.2, size=6 * n)
        keys = raw[raw <= 8][:n].astype(np.int64)  # ~43% on one key
        vals = rng.integers(0, 1000, n).astype(np.int64)
        mem.create_table(
            "default", "facts",
            TableSchema("facts", (ColumnSchema("k", T.BIGINT),
                                  ColumnSchema("v", T.BIGINT))))
        mem.insert("default", "facts",
                   Batch([Column(T.BIGINT, keys), Column(T.BIGINT, vals)], n))
        dk = np.arange(1, 9, dtype=np.int64)
        mem.create_table(
            "default", "dims",
            TableSchema("dims", (ColumnSchema("k", T.BIGINT),
                                 ColumnSchema("name", T.BIGINT))))
        mem.insert("default", "dims",
                   Batch([Column(T.BIGINT, dk), Column(T.BIGINT, dk * 100)],
                         8))

    with tempfile.TemporaryDirectory() as hdir:
        props = {
            "execution_mode": "distributed",
            "join_distribution_type": "PARTITIONED",
            "skew_handling": False,  # force the cold overflow
            "history_dir": hdir,
        }

        def _run(runner):
            return runner.engine.execute_statement(
                sql, Session(properties=props)
            )

        cold_runner = LocalQueryRunner()
        _seed(cold_runner.catalogs)
        cold = _run(cold_runner)
        # FRESH engine: no shared program cache, no in-process stats —
        # only the on-disk history store carries the observed truth over
        warm_runner = LocalQueryRunner()
        _seed(warm_runner.catalogs)
        warm = _run(warm_runner)

    wex = warm.exchange_stats or {}
    cex = cold.exchange_stats or {}
    provs = sorted({
        str(site.get("provenance", "")).split("+")[0]
        for site in (wex.get("capacities") or {}).values()
    })
    return {
        "cold_retries": cex.get("overflow_retries", 0),
        "cold_halvings": cex.get("compile_halvings", 0),
        "cold_strategies": sorted(
            set((cex.get("joinStrategy") or {}).values())),
        "warm_retries": wex.get("overflow_retries", 0),
        "warm_halvings": wex.get("compile_halvings", 0),
        "warm_strategies": sorted(
            set((wex.get("joinStrategy") or {}).values())),
        "warm_provenance": provs,
        "history_seeds": wex.get("history_seeds", 0),
        "drift": warm.rows != cold.rows,
    }


def overload() -> int:
    """4× admission-capacity overload against the event-loop front door.

    Capacity is 2 concurrent queries (hard_concurrency_limit=2); 8
    closed-loop clients keep 4× that admitted-or-waiting at all times,
    while per-tenant token buckets shed their statement bursts with
    503 + Retry-After and the clients' jittered backoff retries carry
    them through. Invariants: admitted queries stay bit-identical to
    their sequential runs, every shed carries Retry-After, and queue
    depth never exceeds the closed-loop bound of one outstanding query
    per client."""
    import threading
    import time
    import urllib.error

    from trino_tpu.client import ClientSession, Connection
    from trino_tpu.config import ServerConfig
    from trino_tpu.engine import Engine
    from trino_tpu.server.http import TrinoTpuServer
    from trino_tpu.server.resourcegroups import (
        GroupConfig,
        ResourceGroupManager,
        Selector,
    )

    clients = 8
    capacity = 2  # offered load is 4x this
    summary: dict = {"scenario": "overload", "partial": True}
    try:
        rgm = ResourceGroupManager(max_wait_seconds=30.0)
        rgm.configure(
            [
                GroupConfig(
                    "root",
                    max_queued=100,
                    hard_concurrency_limit=capacity,
                )
            ],
            [Selector(group="root")],
        )
        engine = Engine()
        server = TrinoTpuServer(
            engine=engine,
            resource_groups=rgm,
            server_config=ServerConfig(
                tenant_rate_limit_qps=20.0,
                tenant_rate_limit_burst=4.0,
                max_inflight_requests=64,
            ),
        ).start()
        sql = (
            "select l_returnflag, sum(l_quantity), count(*)"
            " from tpch.tiny.lineitem where l_quantity < {}"
            " group by l_returnflag order by l_returnflag"
        )
        lits = [10 + 2 * (i % 8) for i in range(clients * 4)]
        from trino_tpu.config import Session

        seq_rows = {
            lit: engine.execute_statement(sql.format(lit), Session()).rows
            for lit in sorted(set(lits))
        }

        # queue-depth monitor: closed-loop clients have at most one
        # statement outstanding each and the burst tenant fires at most
        # burst_posts fire-and-forget statements, so queuedQueries above
        # clients + burst_posts means waiters are leaking (the
        # "unbounded growth" failure mode)
        burst_posts = 8
        peak_queued = [0]
        stop = threading.Event()

        def monitor() -> None:
            while not stop.is_set():
                info = rgm.info()[0]
                peak_queued[0] = max(peak_queued[0], info["queuedQueries"])
                stop.wait(0.02)

        mon = threading.Thread(target=monitor, daemon=True)
        mon.start()

        drift = [0]
        completed = [0]
        errors: list = []
        lock = threading.Lock()

        def client(c: int) -> None:
            conn = Connection(
                server.base_uri,
                ClientSession(user=f"tenant-{c % 4}", shed_retry_attempts=8),
            )
            for r in range(4):
                lit = lits[(r * clients + c) % len(lits)]
                try:
                    rows, _ = conn.execute(sql.format(lit))
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(f"client {c}: {e!r}")
                    continue
                with lock:
                    completed[0] += 1
                    if [list(t) for t in rows] != [
                        list(t) for t in seq_rows[lit]
                    ]:
                        drift[0] += 1

        ts = [
            threading.Thread(target=client, args=(c,)) for c in range(clients)
        ]
        t0 = time.time()
        for t in ts:
            t.start()

        # while the fleet saturates admission, trip the token bucket
        # directly and verify the shed contract: 503 AND Retry-After
        sheds_seen = 0
        bad_sheds = 0
        for _ in range(burst_posts):
            req = urllib.request.Request(
                f"{server.base_uri}/v1/statement",
                data=b"select 1",
                method="POST",
                headers={"X-Trino-User": "burster"},
            )
            try:
                urllib.request.urlopen(req, timeout=10).read()
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    sheds_seen += 1
                    if e.headers.get("Retry-After") is None:
                        bad_sheds += 1
                e.read()

        for t in ts:
            t.join(120)
        stop.set()
        mon.join(2)
        wall = time.time() - t0

        snap = {}
        with urllib.request.urlopen(
            f"{server.base_uri}/v1/metrics?format=json", timeout=10
        ) as r:
            snap = json.loads(r.read().decode())
        shed_counters = {
            k: v
            for k, v in snap.get("counters", {}).items()
            if k.startswith("trino_tpu_requests_shed_total")
        }
        server.stop()

        summary.update(
            {
                "clients": clients,
                "capacity": capacity,
                "completed": completed[0],
                "row_drift": drift[0],
                "errors": errors[:5],
                "peak_queued": peak_queued[0],
                "burst_sheds": sheds_seen,
                "sheds_without_retry_after": bad_sheds,
                "shed_counters": shed_counters,
                "wall_s": round(wall, 2),
                "partial": False,
            }
        )
        if errors:
            print(f"FAIL: overload clients errored: {errors[:3]}")
            summary["ok"] = False
            return 1
        if drift[0]:
            print(f"FAIL: {drift[0]} admitted queries drifted under overload")
            summary["ok"] = False
            return 1
        if completed[0] != clients * 4:
            print(
                f"FAIL: only {completed[0]}/{clients * 4} queries completed"
            )
            summary["ok"] = False
            return 1
        if peak_queued[0] > clients + burst_posts:
            print(
                f"FAIL: queue grew to {peak_queued[0]} with only {clients}"
                f" closed-loop clients + {burst_posts} burst posts —"
                " waiters are leaking"
            )
            summary["ok"] = False
            return 1
        if sheds_seen == 0:
            print("FAIL: burst tenant was never shed — overload never bit")
            summary["ok"] = False
            return 1
        if bad_sheds:
            print(f"FAIL: {bad_sheds} 503s arrived without Retry-After")
            summary["ok"] = False
            return 1
        print(
            "OK: bit-identical under 4x admission overload"
            f" ({completed[0]} queries, {sheds_seen} sheds all carrying"
            " Retry-After, bounded queue)"
        )
        summary["ok"] = True
        return 0
    finally:
        print(json.dumps(summary), flush=True)


def live_append() -> int:
    """Result-cache consistency under a live append: reader threads
    hammer a cached aggregation while a writer appends a part mid-storm.

    Invariants: every observed result equals the pre-append snapshot OR
    the post-append snapshot (atomic entry replacement — never a torn
    mix of old cached rows and new delta rows), and the final read shows
    the appended data. The post-append serve should arrive via
    incremental maintenance (delta splits only); a maintained count of
    zero only WARNs, because the writer can race the version re-check
    and legitimately force an invalidation instead."""
    import threading
    import time

    import numpy as np

    from trino_tpu import types as T
    from trino_tpu.columnar import Batch, Column
    from trino_tpu.config import Session
    from trino_tpu.connectors.api import ColumnSchema, TableSchema
    from trino_tpu.testing import LocalQueryRunner

    readers, iters = 4, 12
    sql = ("select k, sum(v) as s, count(*) as c "
           "from memory.default.live group by k")
    schema = TableSchema("live", (ColumnSchema("k", T.BIGINT),
                                  ColumnSchema("v", T.BIGINT)))
    props = {"execution_mode": "distributed", "result_cache": True,
             "incremental_maintenance": True}
    summary: dict = {"scenario": "live-append", "partial": True}
    try:
        def _batch(n: int, seed: int) -> Batch:
            rng = np.random.default_rng(seed)
            k = rng.integers(0, 9, n).astype(np.int64)
            v = rng.integers(0, 101, n).astype(np.int64)
            return Batch([Column(T.BIGINT, k), Column(T.BIGINT, v)], n)

        part_a, part_b = _batch(4096, 1), _batch(512, 2)

        # ground truth for both table states, from scratch engines with
        # the result cache OFF — the storm's observations must match one
        # of these two snapshots exactly
        def _snap(parts) -> list:
            r = LocalQueryRunner()
            mem = r.catalogs.get("memory")
            mem.create_table("default", "live", schema)
            for p in parts:
                mem.insert("default", "live", p)
            res = r.engine.execute_statement(
                sql, Session(properties={"execution_mode": "distributed"})
            )
            return sorted(map(tuple, res.rows))

        snap_a = _snap([part_a])
        snap_b = _snap([part_a, part_b])

        runner = LocalQueryRunner()
        mem = runner.catalogs.get("memory")
        mem.create_table("default", "live", schema)
        mem.insert("default", "live", part_a)
        runner.engine.execute_statement(sql, Session(properties=props))

        barrier = threading.Barrier(readers + 1)
        lock = threading.Lock()
        torn: list = []
        errors: list = []

        def _reader() -> None:
            barrier.wait()
            for _ in range(iters):
                try:
                    res = runner.engine.execute_statement(
                        sql, Session(properties=props)
                    )
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(repr(e))
                    return
                got = sorted(map(tuple, res.rows))
                if got != snap_a and got != snap_b:
                    with lock:
                        torn.append(got[:3])

        def _writer() -> None:
            barrier.wait()
            time.sleep(0.05)  # let the storm get going first
            mem.insert("default", "live", part_b)

        threads = [threading.Thread(target=_reader) for _ in range(readers)]
        threads.append(threading.Thread(target=_writer))
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)

        final = sorted(map(tuple, runner.engine.execute_statement(
            sql, Session(properties=props)
        ).rows))
        snap = runner.engine.result_cache.snapshot()
        summary.update(
            readers=readers,
            iters=iters,
            torn=len(torn),
            errors=errors[:3],
            hits=snap["hits"],
            maintained=snap["maintained"],
            invalidations=snap["invalidations"],
            partial=False,
        )
        if errors:
            print(f"FAIL: live-append readers errored: {errors[:3]}")
            summary["ok"] = False
            return 1
        if torn:
            print(f"FAIL: {len(torn)} reads saw a torn result (neither the"
                  " pre- nor the post-append snapshot)")
            summary["ok"] = False
            return 1
        if final != snap_b:
            print("FAIL: final read does not show the appended part")
            summary["ok"] = False
            return 1
        if snap["maintained"] == 0:
            print("WARN: append was absorbed by invalidation, not"
                  " incremental maintenance — the writer raced the"
                  " version re-check")
        print(
            "OK: live append stayed atomic under a"
            f" {readers}-reader storm ({snap['hits']} cache hits,"
            f" {snap['maintained']} maintained serves)"
        )
        summary["ok"] = True
        return 0
    finally:
        print(json.dumps(summary), flush=True)


def main() -> int:
    # default seed 3: both partitions of Q1's scan fragment draw below
    # 0.3 on attempt 1 and survive on attempt 2 — guaranteed retries
    seed = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    chaos = {
        "retry_policy": "TASK",
        "task_retry_attempts": 8,
        "fault_injection_seed": seed,
        "fault_task_crash_p": 0.3,
        "retry_initial_delay_ms": 20,
        "retry_max_delay_ms": 200,
    }
    skew_props = {"join_distribution_type": "PARTITIONED"}
    # slow-worker scenario: worker-1 runs every task 10× slower (sleep
    # after compute, before emit — so a speculative cancel can still
    # abort delivery); speculation hedges onto the healthy worker-0
    slow_props = {
        # hedging needs sibling tasks fanned out across workers: a fused
        # pipeline unit is a single task and can never be speculated
        "pipeline_fusion": False,
        "retry_policy": "TASK",
        "fault_injection_seed": seed,
        "fault_slow_workers": "worker-1",
        "fault_task_slow_factor": 10.0,
        "speculation": True,
        "speculation_floor_ms": 100,
        "speculation_multiplier": 2.0,
        "speculation_max_fraction": 1.0,
    }
    # node-death: the worker hosting Q1's scan task (fragment 2,
    # partition 0 — Q1 fragments as root 0 <- partial agg 1 <- scan 2)
    # kills itself 300ms after that task finishes; the 1s pre-execute
    # stall on every task guarantees the fragment-1 consumers pull after
    # the death, so spooled output / lineage recovery must absorb it
    death_props = {
        "retry_policy": "TASK",
        "exchange_spooling": True,
        # pin per-fragment execution: the 2.0 exit site addresses the
        # per-fragment task tree (under the fused default Q1's scan is
        # interior to a unit and the site would never fire); the
        # fused-node-death scenario below covers the fused ladder
        "worker_execution": "per_fragment",
        "task_retry_attempts": 8,
        "retry_initial_delay_ms": 20,
        "retry_max_delay_ms": 200,
        "fault_worker_exit_site": "2.0",
        "fault_worker_exit_delay_ms": 300,
        "fault_task_stall_ms": 1000,
    }
    # the summary dict is built incrementally and emitted in a finally, so
    # a crash mid-scenario still prints one machine-readable JSON line with
    # whatever was gathered (partial: true)
    summary: dict = {"seed": seed, "partial": True}
    try:
        with MultiProcessQueryRunner(n_workers=2) as runner:
            clean, _ = runner.execute(Q1)
            chaotic, _ = runner.execute(Q1, session_properties=chaos)
            skew_clean, _ = runner.execute(
                Q_SKEW, session_properties=skew_props
            )
            skew_chaotic, _ = runner.execute(
                Q_SKEW, session_properties={**chaos, **skew_props}
            )
            slow_spec, _ = runner.execute(Q1, session_properties=slow_props)
            # concurrent-clients: sequential ground truth first, then N
            # threads with batching on; coordinator-local execution
            # (execution_mode=distributed) is where the collector lives
            import threading

            batch_lits = (10, 20, 30, 40)
            batch_props = {
                "execution_mode": "distributed",
                "batch_window_ms": 300,
                "batch_max_size": len(batch_lits),
            }
            seq_batch = {
                lit: runner.execute(
                    Q_BATCH.format(lit),
                    session_properties={"execution_mode": "distributed"},
                )[0]
                for lit in batch_lits
            }
            conc_rows: dict = {}
            conc_errs: dict = {}

            def _client(lit: int) -> None:
                try:
                    conc_rows[lit] = runner.execute(
                        Q_BATCH.format(lit), session_properties=batch_props
                    )[0]
                except Exception as e:  # noqa: BLE001
                    conc_errs[lit] = str(e)

            cthreads = [
                threading.Thread(target=_client, args=(lit,))
                for lit in batch_lits
            ]
            for t in cthreads:
                t.start()
            for t in cthreads:
                t.join()
            # LAST scenario: one worker dies mid-query and stays dead
            death, _ = runner.execute(Q1, session_properties=death_props)
            from trino_tpu.server import auth

            req = urllib.request.Request(
                f"{runner.coordinator_uri}/v1/query", headers=auth.headers()
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                queries = json.loads(r.read().decode())
            # coordinator metrics snapshot (task retries/attempt histograms)
            # must be scraped before the cluster shuts down
            with urllib.request.urlopen(
                f"{runner.coordinator_uri}/v1/metrics?format=json", timeout=10
            ) as r:
                summary["metrics"] = json.loads(r.read().decode())
        # fused-node-death gets its OWN 3-worker cluster: the previous
        # cluster is down a worker for good, and the fused ladder should
        # be measured against a full quorum
        from trino_tpu.server import auth

        fused_site = _fused_unit_site(Q_FUSED, **FUSED_PROPS)
        # post-mortem scenario: the death query journals its lifecycle to
        # an on-disk flight recorder (obs/flight.py); after the cluster is
        # torn down the journal ALONE must explain the recovery
        import tempfile

        flight_tmp = tempfile.mkdtemp(prefix="chaos-flight-")
        fused_death_props = {
            **FUSED_PROPS,
            "retry_policy": "TASK",
            "exchange_spooling": True,
            "task_retry_attempts": 8,
            "retry_initial_delay_ms": 20,
            "retry_max_delay_ms": 200,
            "fault_worker_exit_site": fused_site or "2.0",
            "fault_worker_exit_delay_ms": 300,
            "fault_task_stall_ms": 1000,
            "flight_dir": flight_tmp,
        }
        with MultiProcessQueryRunner(n_workers=3) as runner3:
            fused_clean, _ = runner3.execute(
                Q_FUSED, session_properties=FUSED_PROPS
            )
            fused_death, _ = runner3.execute(
                Q_FUSED, session_properties=fused_death_props
            )
            req = urllib.request.Request(
                f"{runner3.coordinator_uri}/v1/query", headers=auth.headers()
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                fused_queries = json.loads(r.read().decode())
        fused_info = next(
            (
                q
                for q in reversed(fused_queries)
                if q.get("retryPolicy") == "TASK"
            ),
            {},
        )
        # post-mortem: the 3-worker cluster (coordinator included) is
        # gone; read the journal straight off disk and judge it
        from trino_tpu.obs.flight import replay_dir

        pm_events = replay_dir(flight_tmp)
        summary["post_mortem"] = _post_mortem_verdict(pm_events, fused_info)
        summary["fused_node_death"] = {
            "unit_site": fused_site,
            "fused_fragments": (fused_info.get("exchangeStats") or {}).get(
                "fusedFragments", 0
            ),
            "recovered_tasks": fused_info.get("recoveredTasks", 0),
            "recovered_levels": fused_info.get("recoveredTaskLevels", {}),
            "spooled_bytes": fused_info.get("spooledBytes", 0),
            "query_attempts": fused_info.get("queryAttempts", 1),
            "drift": fused_death != fused_clean,
        }
        # star-join gets its OWN 3-worker cluster too: the SIGKILLed
        # worker stays dead, and the multiway ladder deserves a full
        # quorum rather than the fused-node-death cluster's survivors
        star_site = _fused_unit_site(Q_STAR)  # dense_join defaults on
        star_death_props = {
            "retry_policy": "TASK",
            "exchange_spooling": True,
            "task_retry_attempts": 8,
            "retry_initial_delay_ms": 20,
            "retry_max_delay_ms": 200,
            "fault_worker_exit_site": star_site or "2.0",
            "fault_worker_exit_delay_ms": 300,
            "fault_task_stall_ms": 1000,
        }
        with MultiProcessQueryRunner(n_workers=3) as runner4:
            star_clean, _ = runner4.execute(Q_STAR)
            star_death, _ = runner4.execute(
                Q_STAR, session_properties=star_death_props
            )
            req = urllib.request.Request(
                f"{runner4.coordinator_uri}/v1/query", headers=auth.headers()
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                star_queries = json.loads(r.read().decode())
        star_info = next(
            (
                q
                for q in reversed(star_queries)
                if q.get("retryPolicy") == "TASK"
            ),
            {},
        )
        sex = star_info.get("exchangeStats") or {}
        summary["star_join"] = {
            "unit_site": star_site,
            "fused_fragments": sex.get("fusedFragments", 0),
            "join_strategies": sorted(
                set((sex.get("joinStrategy") or {}).values())
            ),
            "recovered_tasks": star_info.get("recoveredTasks", 0),
            "recovered_levels": star_info.get("recoveredTaskLevels", {}),
            "spooled_bytes": star_info.get("spooledBytes", 0),
            "query_attempts": star_info.get("queryAttempts", 1),
            "drift": star_death != star_clean,
        }
        # adaptive-warmup runs in-process (fresh engines + a shared
        # persistent history store), after the clusters are down
        summary["adaptive_warmup"] = _adaptive_warmup(seed)
        retries = max(q.get("taskRetries", 0) for q in queries)
        spec_attempts = max(q.get("speculativeAttempts", 0) for q in queries)
        spec_wins = max(q.get("speculativeWins", 0) for q in queries)
        death_info = max(
            (q for q in queries if q.get("spooledBytes", 0) > 0
             or q.get("recoveredTasks", 0) > 0),
            key=lambda q: q.get("recoveredTasks", 0),
            default={},
        )
        recovered = death_info.get("recoveredTasks", 0)
        spooled = death_info.get("spooledBytes", 0)
        death_attempts = death_info.get("queryAttempts", 1)
        # device-profiler rollup across every scraped query record:
        # FLOPs sum / peak HBM max as merged by the coordinator from
        # worker task stats (all-zero on backends with no cost model)
        device = {"programs_profiled": 0, "total_flops": 0.0,
                  "peak_hbm_bytes": 0}
        for q in queries:
            ds = q.get("deviceStats") or {}
            device["programs_profiled"] += int(
                ds.get("programs_profiled") or 0
            )
            device["total_flops"] += float(ds.get("total_flops") or 0.0)
            device["peak_hbm_bytes"] = max(
                device["peak_hbm_bytes"], int(ds.get("peak_hbm_bytes") or 0)
            )
        summary["device"] = device
        # operator row-flow rollup (exec/fragments.py op! channel) across
        # every scraped query record, incl. the worst partial-agg
        # reduction ratio
        summary["operators"] = _operator_rollup(
            list(queries) + [fused_info, star_info]
        )
        # cross-query batching counters (size-labelled dispatch family)
        batched_counters = {
            k: v
            for k, v in summary.get("metrics", {})
            .get("counters", {})
            .items()
            if k.startswith("trino_tpu_batched_dispatches_total")
        }
        summary["batched_dispatches"] = batched_counters
        summary["concurrent_clients"] = len(batch_lits)
        summary.update(
            seed=seed,
            rows=len(chaotic),
            task_retries=retries,
            speculative_attempts=spec_attempts,
            speculative_wins=spec_wins,
            recovered_tasks=recovered,
            recovered_levels=death_info.get("recoveredTaskLevels", {}),
            spooled_bytes=spooled,
            node_death_query_attempts=death_attempts,
            partial=False,
        )
        print(
            f"seed={seed} rows={len(chaotic)} task_retries={retries}"
            f" speculative_attempts={spec_attempts}"
            f" speculative_wins={spec_wins}"
            f" recovered_tasks={recovered} spooled_bytes={spooled}"
        )
        if chaotic != clean:
            print("FAIL: chaotic result differs from fault-free result")
            summary["ok"] = False
            return 1
        if skew_chaotic != skew_clean:
            print("FAIL: skewed-join chaotic result differs from fault-free")
            summary["ok"] = False
            return 1
        if slow_spec != clean:
            print("FAIL: slow-worker speculative result differs from fault-free")
            summary["ok"] = False
            return 1
        if conc_errs:
            print(f"FAIL: concurrent-clients errors: {conc_errs}")
            summary["ok"] = False
            return 1
        for lit in batch_lits:
            if sorted(conc_rows[lit]) != sorted(seq_batch[lit]):
                print(
                    "FAIL: concurrent-clients row drift at literal"
                    f" {lit} (batched vs sequential)"
                )
                summary["ok"] = False
                return 1
        if not batched_counters:
            print("WARN: no batched dispatches — the window never"
                  " collected concurrent arrivals")
        if death != clean:
            print("FAIL: node-death result differs from fault-free")
            summary["ok"] = False
            return 1
        if death_attempts > 1:
            print(
                "FAIL: node-death escalated to a query-level retry"
                f" (queryAttempts={death_attempts})"
            )
            summary["ok"] = False
            return 1
        fd = summary["fused_node_death"]
        if fd["drift"]:
            print("FAIL: fused-node-death result differs from fault-free")
            summary["ok"] = False
            return 1
        if fd["query_attempts"] > 1:
            print(
                "FAIL: fused-node-death escalated to a query-level retry"
                f" (queryAttempts={fd['query_attempts']})"
            )
            summary["ok"] = False
            return 1
        if fd["fused_fragments"] == 0:
            print("FAIL: fused-node-death query never fused — the scenario"
                  " silently exercised the per-fragment path")
            summary["ok"] = False
            return 1
        if fd["recovered_tasks"] == 0:
            print("WARN: fused-node-death recovered nothing — the unit"
                  " death raced the consumer pull")
        pm = summary["post_mortem"]
        if not pm["explains_recovery"]:
            bad = [k for k, v in pm["checks"].items() if not v]
            print(
                "FAIL: post-mortem — flight journal alone does not explain"
                f" the fused-node-death recovery (failed checks: {bad})"
            )
            summary["ok"] = False
            return 1
        sj = summary["star_join"]
        if sj["drift"]:
            print("FAIL: star-join result differs from fault-free")
            summary["ok"] = False
            return 1
        if sj["query_attempts"] > 1:
            print(
                "FAIL: star-join escalated to a query-level retry"
                f" (queryAttempts={sj['query_attempts']})"
            )
            summary["ok"] = False
            return 1
        if sj["fused_fragments"] == 0:
            print("FAIL: star-join query never fused — the multiway"
                  " broadcast absorption silently did not happen")
            summary["ok"] = False
            return 1
        if "dense" not in sj["join_strategies"]:
            print(
                "FAIL: star-join ran without the dense tier"
                f" (joinStrategy={sj['join_strategies']}) — the scenario"
                " exercised the sort path instead"
            )
            summary["ok"] = False
            return 1
        if sj["recovered_tasks"] == 0:
            print("WARN: star-join recovered nothing — the unit death"
                  " raced the consumer pull")
        aw = summary["adaptive_warmup"]
        if aw["drift"]:
            print("FAIL: adaptive-warmup warm result differs from cold")
            summary["ok"] = False
            return 1
        if aw["warm_retries"] != 0 or aw["warm_halvings"] != 0:
            print(
                "FAIL: adaptive-warmup warm run still corrected itself"
                f" (overflow_retries={aw['warm_retries']},"
                f" compile_halvings={aw['warm_halvings']}) — history"
                " seeding did not carry the observed capacities over"
            )
            summary["ok"] = False
            return 1
        learned = aw["cold_retries"] > 0 or aw["cold_halvings"] > 0
        if learned and "history" not in aw["warm_provenance"]:
            print(
                "FAIL: adaptive-warmup cold run grew a capacity but the"
                " warm run has no history-seeded site"
                f" (provenance={aw['warm_provenance']})"
            )
            summary["ok"] = False
            return 1
        if aw["warm_strategies"] != ["matmul"]:
            print(
                "FAIL: adaptive-warmup warm run did not take the"
                " history-driven matmul promotion (cold"
                f" {aw['cold_strategies']} -> warm {aw['warm_strategies']})"
                " — the recorded dense-join domain never reached the cost"
                " gate"
            )
            summary["ok"] = False
            return 1
        if aw["cold_retries"] == 0:
            print("WARN: adaptive-warmup cold run never overflowed — the"
                  " warm zero-retry check only proves the strategy loop"
                  " at this size")
        if recovered == 0:
            print("WARN: no recovered tasks — the worker-exit fault"
                  " never bit a consumer")
        if retries == 0:
            print("WARN: no retries at this seed — injection never fired")
        if spec_attempts == 0:
            print("WARN: no speculative attempts — straggler never flagged")
        print(
            "OK: bit-identical under 30% task-crash injection"
            " (incl. skewed join, 10x slow worker, concurrent batched"
            " clients, node death, fused node death, multiway star join,"
            " adaptive warmup)"
        )
        summary["ok"] = True
        return 0
    finally:
        print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "overload":
        sys.exit(overload())
    if len(sys.argv) > 1 and sys.argv[1] == "live-append":
        sys.exit(live_append())
    sys.exit(main())
