"""Chaos smoke: one TPC-H query under 30% task-crash injection.

Boots a 2-worker cluster, runs TPC-H Q1 twice — fault-free, then with
``fault_task_crash_p=0.3`` + ``retry_policy=TASK`` — and checks the
results are bit-identical and that at least one task retry happened.
Quick manual repro for the fault-tolerance stack (CI runs the same
scenario as ``tests/test_fault_tolerance.py -m faults``).

Usage: JAX_PLATFORMS=cpu python scripts/chaos_smoke.py [seed]
"""

import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from trino_tpu.testing import MultiProcessQueryRunner

Q1 = """select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
              sum(l_extendedprice) as sum_base_price,
              avg(l_discount) as avg_disc, count(*) as count_order
       from lineitem where l_shipdate <= date '1998-09-02'
       group by l_returnflag, l_linestatus
       order by l_returnflag, l_linestatus"""


def main() -> int:
    # default seed 3: both partitions of Q1's scan fragment draw below
    # 0.3 on attempt 1 and survive on attempt 2 — guaranteed retries
    seed = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    chaos = {
        "retry_policy": "TASK",
        "task_retry_attempts": 8,
        "fault_injection_seed": seed,
        "fault_task_crash_p": 0.3,
        "retry_initial_delay_ms": 20,
        "retry_max_delay_ms": 200,
    }
    with MultiProcessQueryRunner(n_workers=2) as runner:
        clean, _ = runner.execute(Q1)
        chaotic, _ = runner.execute(Q1, session_properties=chaos)
        from trino_tpu.server import auth

        req = urllib.request.Request(
            f"{runner.coordinator_uri}/v1/query", headers=auth.headers()
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            queries = json.loads(r.read().decode())
    retries = max(q.get("taskRetries", 0) for q in queries)
    print(f"seed={seed} rows={len(chaotic)} task_retries={retries}")
    if chaotic != clean:
        print("FAIL: chaotic result differs from fault-free result")
        return 1
    if retries == 0:
        print("WARN: no retries at this seed — injection never fired")
    print("OK: bit-identical under 30% task-crash injection")
    return 0


if __name__ == "__main__":
    sys.exit(main())
