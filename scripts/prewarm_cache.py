"""Pre-warm the persistent JAX compile cache (ROADMAP tier-1 runtime item).

Traces and compiles the fragment/kernel shapes the test suite hits most —
scan→agg, partitioned join (skewed and plain), streaming group-by — so a
CI rerun that points ``JAX_COMPILATION_CACHE_DIR`` at the same directory
skips those compiles. Run from the repo root:

    JAX_COMPILATION_CACHE_DIR=.jax_cache python scripts/prewarm_cache.py

With ``--history-dir DIR`` (a query-history store written by a prior
serving run, obs/history.py) the corpus is reordered by OBSERVED elapsed
— the slowest fingerprints the store has seen warm first, ``--top N``
bounds how many history-ranked entries run — and a fingerprint →
observed-stats table prints what the history knew about each.

With ``--results`` the corpus is additionally executed with the semantic
result cache enabled and a fingerprint → cached-bytes table prints what
landed in the RESULT tier (see README "Semantic result cache").

The suite's conftest honors the same variable, so tests reuse the warmed
entries. Idempotent: re-running only adds missing entries.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--history-dir", default="",
        help="query-history store directory: rank the corpus by observed "
             "elapsed (slowest first) instead of static order",
    )
    ap.add_argument(
        "--top", type=int, default=0,
        help="with --history-dir: only prewarm the N slowest "
             "history-known fingerprints (0 = all, history-known first)",
    )
    ap.add_argument(
        "--results", action="store_true",
        help="also populate the semantic RESULT cache (re-run the corpus "
             "with result_cache=on) and print a fingerprint -> "
             "cached-bytes table",
    )
    args = ap.parse_args()

    import jax

    cache_dir = os.path.abspath(
        os.environ.get("JAX_COMPILATION_CACHE_DIR") or ".jax_cache"
    )
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # write EVERY compile: the suite reads entries regardless of its own
    # write threshold, and CPU-CI compiles are individually fast but
    # collectively the tier-1 tail
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    import numpy as np

    from trino_tpu import types as T  # noqa: F401 — import applies config

    # trino_tpu's import hook re-applies cache config; restore ours after
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    from trino_tpu import types as T
    from trino_tpu.columnar import Batch, Column
    from trino_tpu.config import Session
    from trino_tpu.connectors.api import ColumnSchema, TableSchema
    from trino_tpu.testing import LocalQueryRunner

    t0 = time.time()
    runner = LocalQueryRunner()
    mem = runner.catalogs.get("memory")
    rng = np.random.default_rng(0)
    n = 1 << 16
    keys = (rng.zipf(1.2, size=6 * n)[: 6 * n] % 64 + 1)[:n].astype(np.int64)
    vals = rng.integers(0, 1000, n).astype(np.int64)
    mem.create_table(
        "default", "warm_facts",
        TableSchema("warm_facts", (ColumnSchema("k", T.BIGINT),
                                   ColumnSchema("v", T.BIGINT))),
    )
    mem.insert("default", "warm_facts",
               Batch([Column(T.BIGINT, keys), Column(T.BIGINT, vals)], n))
    dk = np.arange(1, 65, dtype=np.int64)
    mem.create_table(
        "default", "warm_dims",
        TableSchema("warm_dims", (ColumnSchema("k", T.BIGINT),
                                  ColumnSchema("name", T.BIGINT))),
    )
    mem.insert("default", "warm_dims",
               Batch([Column(T.BIGINT, dk), Column(T.BIGINT, dk * 10)], 64))

    shapes = [
        # scan -> global agg (single exchange)
        ("select count(*), sum(v) from memory.default.warm_facts", {}),
        # scan -> group-by (hash exchange + final agg)
        ("select k, sum(v) from memory.default.warm_facts group by k", {}),
        # filtered group-by + two literal variants: constant hoisting
        # canonicalizes all three to ONE fingerprint, so the corpus below
        # dedupes them to a single compile (the printed table proves it)
        ("select k, sum(v) from memory.default.warm_facts "
         "where v < 100 group by k", {}),
        ("select k, sum(v) from memory.default.warm_facts "
         "where v < 500 group by k", {}),
        ("select k, sum(v) from memory.default.warm_facts "
         "where v < 900 group by k", {}),
        # partitioned join, skew path on (detect + salt programs)
        ("select sum(f.v * d.name) from memory.default.warm_facts f "
         "join memory.default.warm_dims d on f.k = d.k",
         {"join_distribution_type": "PARTITIONED"}),
        # same join, plain two-tier path
        ("select sum(f.v * d.name) from memory.default.warm_facts f "
         "join memory.default.warm_dims d on f.k = d.k",
         {"join_distribution_type": "PARTITIONED", "skew_handling": False}),
        # TPC-H tiny shapes the suites lean on
        ("select l_returnflag, sum(l_quantity) from tpch.tiny.lineitem "
         "group by l_returnflag", {}),
    ]
    # --history-dir: rank the corpus by what a prior serving run OBSERVED.
    # The store keys on plan fingerprints, not SQL, so each corpus entry's
    # fingerprint is matched against the store; known-slow shapes warm
    # first (they are the compiles worth paying for), unknown shapes keep
    # corpus order after them, and --top N keeps only the N slowest
    # history-known entries plus the unknowns.
    if args.history_dir:
        from trino_tpu.obs.history import QueryHistoryStore

        store = QueryHistoryStore(
            path=os.path.join(args.history_dir, "query_history.json")
        )
        observed = dict(store.entries())
        ranked, unknown = [], []
        for sql, props in shapes:
            try:
                fp, _ = runner.engine.fingerprint(
                    sql,
                    Session(properties={"execution_mode": "distributed",
                                        **props}),
                )
            except Exception:
                fp = None
            ent = observed.get(fp) if fp else None
            (ranked if ent else unknown).append((sql, props, fp, ent))
        ranked.sort(key=lambda r: -float(r[3].get("elapsed_ms") or 0.0))
        if args.top > 0:
            for sql, props, fp, _ent in ranked[args.top:]:
                print(f"below-top skip {fp[:12] if fp else '?':<12} "
                      f"{sql.split(chr(10))[0][:52]}")
            ranked = ranked[: args.top]
        if ranked:
            print(f"history {store.path or '(memory)'}: "
                  f"{len(observed)} fingerprints, "
                  f"{len(ranked)} matched in corpus\n")
            print("fingerprint   count  p50 ms  retries  halvings  "
                  "peak HBM B  query")
            for sql, _props, fp, ent in ranked:
                print(f"{fp[:12]}  {ent.get('count', 0):>5}  "
                      f"{float(ent.get('elapsed_p50_ms') or 0.0):>6.1f}  "
                      f"{ent.get('overflow_retries', 0):>7}  "
                      f"{ent.get('compile_halvings', 0):>8}  "
                      f"{ent.get('peak_hbm_bytes', 0):>10}  "
                      f"{sql.split(chr(10))[0][:40]}")
            print()
        else:
            print(f"history {store.path or '(memory)'}: no corpus entry "
                  "matches a stored fingerprint; static order\n")
        shapes = [(sql, props) for sql, props, _fp, _e in ranked + unknown]

    # one representative per canonical plan shape: literal variants share
    # a fingerprint, so executing the first warms the program cache (and
    # the persistent XLA cache) for every other member of the family
    seen_fps: dict[str, str] = {}
    for sql, props in shapes:
        for mode in ("local", "distributed"):
            s = Session(properties={"execution_mode": mode, **props})
            label = sql.split(chr(10))[0][:60]
            try:
                fp = None
                if mode == "distributed":
                    fp, _params = runner.engine.fingerprint(sql, s)
                    if fp is not None and fp in seen_fps:
                        print(f"dedup  [{mode}] {label} "
                              f"(= {fp[:12]} already warmed)")
                        continue
                runner.engine.execute_statement(sql, s)
                if fp is not None:
                    seen_fps[fp] = label
                print(f"warmed [{mode}] {label}")
            except Exception as e:  # noqa: BLE001 — warm what we can
                print(f"skip   [{mode}] {type(e).__name__}: {e}")
    # fingerprint -> compiled-program table (engine program cache)
    cache = getattr(runner.engine, "_query_cache", {})
    if cache:
        print("\nfingerprint   programs  query")
        for key, entry in cache.items():
            fp = key[0] if isinstance(key, tuple) else str(key)
            print(f"{fp[:12]}  {len(entry.get('programs', {})):>8}  "
                  f"{seen_fps.get(fp, '?')}")
    # --results: re-run the corpus with the semantic result cache on so a
    # serving run that shares this engine (or reads /v1/cache) starts with
    # warm RESULT entries, then print what got cached. Literal variants
    # that share a fingerprint still store separately (the param vector is
    # part of the entry key), so the table can show more rows than the
    # compile table above.
    if args.results:
        for sql, props in shapes:
            s = Session(properties={"execution_mode": "distributed",
                                    "result_cache": True, **props})
            try:
                runner.engine.execute_statement(sql, s)
            except Exception as e:  # noqa: BLE001 — warm what we can
                print(f"skip   [result] {type(e).__name__}: {e}")
        snap = runner.engine.result_cache.snapshot()
        print("\nfingerprint   rows     bytes  maint  query")
        for ent in snap["entries"]:
            fp = ent["fingerprint"] or "?"
            print(f"{fp[:12]}  {ent['rows']:>4}  {ent['nbytes']:>8}  "
                  f"{'yes' if ent['maintainable'] else ' no':>5}  "
                  f"{ent['query'][:48]}")
        print(f"result cache: {len(snap['entries'])} entries, "
              f"{snap['totalBytes']} / {snap['maxBytes']} bytes")
    n_entries = (
        len(os.listdir(cache_dir)) if os.path.isdir(cache_dir) else 0
    )
    print(
        f"cache dir {cache_dir}: {n_entries} entries, "
        f"{time.time() - t0:.1f}s"
    )


if __name__ == "__main__":
    main()
