"""Repro harness for cluster exchange timeouts (debug tool)."""

import json
import sys
import threading
import time
import urllib.request

sys.path.insert(0, "/root/repo")

from trino_tpu.testing import MultiProcessQueryRunner

Q3 = """select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
              o_orderdate, o_shippriority
       from customer, orders, lineitem
       where c_mktsegment = 'BUILDING'
         and c_custkey = o_custkey and l_orderkey = o_orderkey
         and o_orderdate < date '1995-03-15'
         and l_shipdate > date '1995-03-15'
       group by l_orderkey, o_orderdate, o_shippriority
       order by revenue desc, o_orderdate limit 10"""


def dump_tasks(runner):
    from trino_tpu.server import auth

    for uri in [runner.coordinator_uri] + runner.worker_uris:
        try:
            req = urllib.request.Request(f"{uri}/v1/task", headers=auth.headers())
            with urllib.request.urlopen(req, timeout=5) as r:
                tasks = json.loads(r.read().decode())
            print(f"--- {uri}")
            for t in tasks:
                print("   ", t)
        except Exception as e:
            print(f"--- {uri}: {e}")


def main():
    with MultiProcessQueryRunner(n_workers=2) as runner:
        t0 = time.time()
        done = threading.Event()
        result = {}

        def run():
            try:
                result["rows"], _ = runner.execute(Q3)
            except Exception as e:
                result["error"] = repr(e)[:2000]
            done.set()

        threading.Thread(target=run, daemon=True).start()
        if not done.wait(timeout=60):
            print(f"HUNG after 60s; task states:")
            dump_tasks(runner)
            for i, log in enumerate(runner._logs):
                print(f"=== proc {i} log tail:")
                print("".join(log[-30:]))
            return
        print(f"finished in {time.time()-t0:.1f}s: {list(result)[0]}")
        if "error" in result:
            print(result["error"])
            dump_tasks(runner)
        for i, log in enumerate(runner._logs):
            if log:
                print(f"=== proc {i} log tail:")
                print("".join(log[-30:]))


if __name__ == "__main__":
    main()
