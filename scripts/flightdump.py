#!/usr/bin/env python
"""Offline flight-journal reader (obs/flight.py).

Replays a coordinator's crash-safe query journal straight from disk — no
server, no live process — and prints it as a timeline or JSON. This is
the post-mortem tool for a coordinator that is not coming back: the
journal's intact prefix survives SIGKILL mid-write by construction
(length-prefixed CRC records; replay stops at the first torn record).

    python scripts/flightdump.py /var/trino-tpu/flight
    python scripts/flightdump.py /var/trino-tpu/flight --query 20260807_...
    python scripts/flightdump.py /var/trino-tpu/flight --json
    python scripts/flightdump.py /var/trino-tpu/flight --events completed
"""

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from trino_tpu.obs.flight import replay_dir  # noqa: E402


def _fmt_ts(ts) -> str:
    try:
        return time.strftime("%H:%M:%S", time.localtime(float(ts))) + (
            "%.3f" % (float(ts) % 1.0)
        )[1:]
    except (TypeError, ValueError):
        return "?"


def _summarize(rec: dict) -> str:
    """One timeline line per record; the completed record carries the
    post-mortem payload, so surface its verdict inline."""
    event = rec.get("event", "?")
    bits = []
    if event == "completed":
        bits.append(f"state={rec.get('state')}")
        bits.append(f"wallMs={rec.get('wallMs')}")
        if (rec.get("queryAttempts") or 1) > 1:
            bits.append(f"attempts={rec.get('queryAttempts')}")
        if rec.get("taskRetries"):
            bits.append(f"taskRetries={rec.get('taskRetries')}")
        if rec.get("recoveredTasks"):
            bits.append(f"recovered={rec.get('recoveredTasks')}")
        err = rec.get("error")
        if err:
            bits.append(f"error={err.get('errorName')}")
        ops = rec.get("operatorStats") or {}
        if ops:
            bits.append(f"operators={len(ops)}")
        reg = (rec.get("queryStats") or {}).get("regression")
        if reg:
            bits.append(
                f"REGRESSED x{reg.get('magnitude')} ({reg.get('severity')})"
            )
    elif event == "retry":
        bits.append(f"attempt={rec.get('attempt')}")
        bits.append(f"error={rec.get('errorClass')}")
    elif event == "running":
        bits.append(f"queuedMs={rec.get('queuedMs')}")
    elif event in ("rejected", "canceled", "killed"):
        bits.append(str(rec.get("error") or rec.get("message") or ""))
    elif event in ("admitted", "queued"):
        if rec.get("group"):
            bits.append(f"group={rec.get('group')}")
    elif event == "created":
        q = str(rec.get("query") or "").replace("\n", " ")
        bits.append(q[:60] + ("…" if len(q) > 60 else ""))
    return " ".join(str(b) for b in bits if b)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("directory", help="flight journal directory")
    ap.add_argument("--query", help="filter to one query id")
    ap.add_argument(
        "--events", help="comma-separated event filter (e.g. completed,retry)"
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit full records as JSON lines (everything the journal has)",
    )
    args = ap.parse_args(argv)

    records = replay_dir(args.directory, args.query)
    if args.events:
        wanted = {e.strip() for e in args.events.split(",") if e.strip()}
        records = [r for r in records if r.get("event") in wanted]
    if args.json:
        for rec in records:
            print(json.dumps(rec, default=str))
        return 0
    if not records:
        print(f"no flight records under {args.directory}", file=sys.stderr)
        return 1
    for rec in records:
        print(
            f"{_fmt_ts(rec.get('ts'))}  {rec.get('queryId', '?'):<32}"
            f"  {rec.get('event', '?'):<10} {_summarize(rec)}"
        )
    print(f"-- {len(records)} records", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
