#!/usr/bin/env python
"""Standalone entry point for the repo's static analysis.

Equivalent to ``python -m trino_tpu.lint``; exists so the lint can run
without the package on ``sys.path`` (e.g. from a CI checkout or a git
hook). Typical use:

    python scripts/lint.py                       # gate: new violations fail
    python scripts/lint.py --no-baseline         # show all findings
    python scripts/lint.py --update-baseline     # accept current findings
    python scripts/lint.py --only concurrency    # one rule family
    python scripts/lint.py --stats               # per-rule counts
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from trino_tpu.lint.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
