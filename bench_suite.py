"""BASELINE benchmark suite: per-query engine wall times + scan rates.

Covers the BASELINE.json evaluation configs beyond the single-kernel
headline in bench.py:
- config 2: TPC-H SF1 Q1/Q3/Q5/Q10 engine wall time (SQL in -> rows out,
  spec dbgen data, streamed joins for the lineitem probes)
- config 3: TPC-DS Q64 (full two-CTE text) + Q95 engine wall time
- config 5: columnar scan+decode rate (GB/s) for parquet and ORC files
  written from dbgen lineitem

Reference harness shape:
``testing/trino-benchto-benchmarks/src/main/resources/benchmarks/presto/
tpch.yaml`` (6 runs, prewarm) — here: one warm run then median of 3.

HANG-PROOFING: ``run_suite`` executes every measurement in its OWN
subprocess with a hard timeout — one pathological XLA compile cannot
wedge the chip for the rest of the suite (a SIGTERM'd compile leaves a
native thread holding the TPU, so the poisoned child is SIGKILLed and
the next child gets a fresh client). A timed-out entry reports
``{"timeout": <seconds>}`` instead of wedging.

Run directly for a readable report, or let bench.py embed the dict in
its one-line JSON.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def _median_time(runner, sql: str, runs: int = 3) -> float:
    runner.execute(sql)  # warm: compile + staging + program cache
    times = []
    for _ in range(runs):
        t0 = time.time()
        runner.execute(sql)
        times.append(time.time() - t0)
    times.sort()
    return times[len(times) // 2]


def _operator_rollup(operator_stats) -> dict:
    """In-program operator telemetry rollup (exec/fragments.py op!
    channel): total rows in/out per operator kind, plus the WORST
    partial-agg reduction ratio (rows_out/rows_in — highest = the
    exchange whose partial agg reduced least, i.e. the best candidate
    for skipping partial aggregation)."""
    out: dict = {}
    worst = None
    for ent in (operator_stats or {}).values():
        kind = str(ent.get("kind") or "")
        if not kind:
            continue
        key = kind.replace("-", "_")
        rin = int(ent.get("rows_in", 0) or 0)
        rout = int(ent.get("rows_out", 0) or 0)
        out[f"op_{key}_rows_in"] = out.get(f"op_{key}_rows_in", 0) + rin
        out[f"op_{key}_rows_out"] = out.get(f"op_{key}_rows_out", 0) + rout
        if kind == "partial-agg" and rin > 0:
            ratio = rout / rin
            worst = ratio if worst is None else max(worst, ratio)
    if worst is not None:
        out["op_worst_partial_agg_reduction"] = round(worst, 4)
    return out


def _dispatch_stats(runner, sql: str) -> dict:
    """Pipeline-fusion telemetry for one warm run: how many device
    dispatches the query costs (fused chains collapse N fragment
    dispatches into 1) and how many fragments rode in fused programs —
    plus the per-kind operator row-flow rollup."""
    res = runner.engine.execute_statement(sql, runner.session)
    ex = res.exchange_stats or {}
    out = {}
    if ex.get("dispatchRoundTrips") is not None:
        out["dispatch_round_trips"] = ex["dispatchRoundTrips"]
    if ex.get("fusedFragments"):
        out["fused_fragments"] = ex["fusedFragments"]
    out.update(_operator_rollup(getattr(res, "operator_stats", None)))
    return out


def tpch_sf1(queries=(1, 3, 5, 10)) -> dict:
    from trino_tpu.benchmarks.tpch import queries as corpus
    from trino_tpu.testing import LocalQueryRunner

    runner = LocalQueryRunner()
    runner.session.set("execution_mode", "distributed")
    texts = corpus("tpch.sf1")
    out = {}
    for q in queries:
        out[f"q{q:02d}_s"] = round(_median_time(runner, texts[q]), 3)
        for k, v in _dispatch_stats(runner, texts[q]).items():
            out[f"q{q:02d}_{k}"] = v
    return out


def tpcds_q(qnum: int) -> dict:
    """Config 3: one TPC-DS query by number (full corpus text)."""
    from trino_tpu.benchmarks.tpcds import queries as corpus
    from trino_tpu.testing import LocalQueryRunner

    runner = LocalQueryRunner()
    runner.session.set("execution_mode", "distributed")
    texts = corpus("tpcds.tiny")
    out = {f"q{qnum}_s": round(_median_time(runner, texts[qnum]), 3)}
    for k, v in _dispatch_stats(runner, texts[qnum]).items():
        out[f"q{qnum}_{k}"] = v
    return out


def columnar_scan_rates(sf: float = 0.1) -> dict:
    """Write dbgen lineitem once as parquet and ORC, then measure the
    engine's scan+decode rate over the files (config 5 shape)."""
    import tempfile

    from trino_tpu.testing import LocalQueryRunner

    runner = LocalQueryRunner()
    runner.session.set("execution_mode", "distributed")
    rows, _ = runner.execute(
        "select l_orderkey, l_quantity, l_extendedprice, l_discount,"
        " l_shipdate from tpch.tiny.lineitem"
    )
    out = {}
    with tempfile.TemporaryDirectory() as td:
        import numpy as np
        import pyarrow as pa
        import pyarrow.orc as paorc
        import pyarrow.parquet as papq

        table = pa.table(
            {
                "l_orderkey": np.asarray([r[0] for r in rows], np.int64),
                "l_quantity": np.asarray([float(r[1]) for r in rows]),
                "l_extendedprice": np.asarray([float(r[2]) for r in rows]),
                "l_discount": np.asarray([float(r[3]) for r in rows]),
            }
        )
        reps = max(1, int(sf * 6_000_000 / max(1, len(rows))))
        table = pa.concat_tables([table] * reps)
        os.makedirs(os.path.join(td, "default", "li"))
        pq_path = os.path.join(td, "default", "li", "part0.parquet")
        orc_path = os.path.join(td, "default", "li", "part0.orc")
        papq.write_table(table, pq_path)
        paorc.write_table(table, orc_path)
        from trino_tpu.connectors.parquet import ParquetConnector
        from trino_tpu.connectors.orc import OrcConnector

        runner.engine.catalogs.register("bpq", ParquetConnector(td))
        runner.engine.catalogs.register("borc", OrcConnector(td))
        for cat, path, name in (
            ("bpq", pq_path, "parquet"),
            ("borc", orc_path, "orc"),
        ):
            sql = (
                f"select sum(l_extendedprice), count(*) from {cat}.default.li"
            )
            dt = _median_time(runner, sql)
            nbytes = os.path.getsize(path)
            out[f"{name}_scan_gbps"] = round(nbytes / dt / 1e9, 3)
            out[f"{name}_scan_s"] = round(dt, 3)
    return out


def parquet_table_cache(sf: float = 0.05) -> dict:
    """Scan-from-Parquet with cold/warm splits: the cold run pays split
    decode + coalesced H2D through the ingest tier (trino_tpu/ingest.py);
    warm repeats hit the device-resident table cache and must report
    h2d_bytes == 0. The warm/cold ratio is the table-cache win."""
    import tempfile

    from trino_tpu.testing import LocalQueryRunner

    runner = LocalQueryRunner()
    runner.session.set("execution_mode", "distributed")
    # keep the scan on the fragment path, where the table cache lives
    runner.session.set("stream_scan_threshold_rows", 1 << 26)
    # the benchmark measures the arena path even at small sf
    runner.session.set("coalesce_min_bytes", 0)
    rows, _ = runner.execute(
        "select l_orderkey, l_quantity, l_extendedprice, l_discount"
        " from tpch.tiny.lineitem"
    )
    out: dict = {}
    with tempfile.TemporaryDirectory() as td:
        import numpy as np
        import pyarrow as pa
        import pyarrow.parquet as papq

        table = pa.table(
            {
                "l_orderkey": np.asarray([r[0] for r in rows], np.int64),
                "l_extendedprice": np.asarray(
                    [float(r[2]) for r in rows], np.float32
                ),
            }
        )
        reps = max(1, int(sf * 6_000_000 / max(1, len(rows))))
        table = pa.concat_tables([table] * reps)
        os.makedirs(os.path.join(td, "default", "li"))
        papq.write_table(
            table, os.path.join(td, "default", "li", "part0.parquet")
        )
        from trino_tpu.connectors.parquet import ParquetConnector

        runner.engine.catalogs.register("bpq", ParquetConnector(td))
        sql = "select sum(l_extendedprice), count(*) from bpq.default.li"
        t0 = time.time()
        cold = runner.engine.execute_statement(sql, runner.session)
        out["cold_s"] = round(time.time() - t0, 3)
        ing = cold.ingest_stats or {}
        out["cold_h2d_bytes"] = ing.get("h2d_bytes", 0)
        out["cold_decode_ms"] = ing.get("decode_ms", 0.0)
        times = []
        warm = cold
        for _ in range(3):
            t0 = time.time()
            warm = runner.engine.execute_statement(sql, runner.session)
            times.append(time.time() - t0)
        times.sort()
        out["warm_s"] = round(times[len(times) // 2], 3)
        wing = warm.ingest_stats or {}
        out["warm_h2d_bytes"] = wing.get("h2d_bytes", 0)  # 0 on cache hit
        out["warm_cache_hits"] = wing.get("table_cache_hits", 0)
        out["rows"] = table.num_rows
    return out


def adaptive_history(n_rows: int = 1 << 16) -> dict:
    """Cold vs history-warm on a Zipf-skewed partitioned join with skew
    handling off: the cold engine overflow-retries its way to the right
    capacities and records them into a persistent query-history store
    (obs/history.py); a FRESH engine sharing the same ``history_dir``
    then repeats the query seeded from observed truth. Reports the
    retry/halving delta and the wall-time ratio — the history win is
    the recompiles the warm run never pays."""
    import tempfile

    import numpy as np

    from trino_tpu import types as T
    from trino_tpu.columnar import Batch, Column
    from trino_tpu.config import Session
    from trino_tpu.connectors.api import ColumnSchema, TableSchema
    from trino_tpu.testing import LocalQueryRunner

    sql = ("select sum(f.v * d.name) as chk, count(*) as c "
           "from memory.default.facts f "
           "join memory.default.dims d on f.k = d.k")

    def _seed(catalogs):
        mem = catalogs.get("memory")
        rng = np.random.default_rng(7)
        raw = rng.zipf(1.2, size=6 * n_rows)
        keys = raw[raw <= 8][:n_rows].astype(np.int64)
        vals = rng.integers(0, 1000, n_rows).astype(np.int64)
        mem.create_table(
            "default", "facts",
            TableSchema("facts", (ColumnSchema("k", T.BIGINT),
                                  ColumnSchema("v", T.BIGINT))))
        mem.insert(
            "default", "facts",
            Batch([Column(T.BIGINT, keys), Column(T.BIGINT, vals)], n_rows))
        dk = np.arange(1, 9, dtype=np.int64)
        mem.create_table(
            "default", "dims",
            TableSchema("dims", (ColumnSchema("k", T.BIGINT),
                                 ColumnSchema("name", T.BIGINT))))
        mem.insert("default", "dims",
                   Batch([Column(T.BIGINT, dk), Column(T.BIGINT, dk * 100)],
                         8))

    out: dict = {"rows": n_rows}
    with tempfile.TemporaryDirectory() as hdir:
        props = {
            "execution_mode": "distributed",
            "join_distribution_type": "PARTITIONED",
            "skew_handling": False,  # capacity misses land on retries
            "history_dir": hdir,
        }

        def _phase(label):
            # fresh runner per phase: only the on-disk store carries over
            runner = LocalQueryRunner()
            _seed(runner.catalogs)
            t0 = time.time()
            res = runner.engine.execute_statement(
                sql, Session(properties=props)
            )
            out[f"{label}_s"] = round(time.time() - t0, 3)
            ex = res.exchange_stats or {}
            out[f"{label}_overflow_retries"] = ex.get("overflow_retries", 0)
            out[f"{label}_compile_halvings"] = ex.get("compile_halvings", 0)
            out[f"{label}_history_seeds"] = ex.get("history_seeds", 0)
            return res.rows

        cold = _phase("cold")
        warm = _phase("warm")
    out["identical"] = warm == cold
    out["retry_delta"] = (
        out["cold_overflow_retries"] - out["warm_overflow_retries"]
    )
    out["halving_delta"] = (
        out["cold_compile_halvings"] - out["warm_compile_halvings"]
    )
    if out["warm_s"] > 0:
        out["speedup"] = round(out["cold_s"] / out["warm_s"], 2)
    return out


def bench_join(log2_rows=(16, 18, 20), probe_factor: int = 1) -> dict:
    """Join engine v2 microbench: the sort (bitonic), dense (open
    addressing) and matmul (identity binned) tiers over the same
    pre-staged device keys, at 2^16..2^22 build rows.

    Each tier runs the whole hash->build->probe->verify pipeline under
    one jit; the published ``*_rows_per_sec_per_chip`` is probe rows
    over median wall time on one device. ``overflow_fallbacks`` counts
    build-table/output overflows observed while timing — the graceful
    ladder means the number must be 0 (nothing ever drops to the
    interpreter)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from trino_tpu.ops import dense_join as DJ
    from trino_tpu.ops.join import build_side, hash_keys, probe_join, verify_equal

    out: dict = {"chips": 1, "probe_factor": probe_factor}
    fallbacks = 0
    for lg in log2_rows:
        n = 1 << lg
        npr = n * probe_factor
        cap = 4 * n  # the executor's default table load factor
        out_cap = 2 * npr
        rng = np.random.default_rng(17)
        bk = jnp.asarray(rng.permutation(n).astype(np.int64))
        pk = jnp.asarray(rng.integers(0, 2 * n, npr).astype(np.int64))
        ones_b = jnp.ones(n, jnp.bool_)
        ones_p = jnp.ones(npr, jnp.bool_)

        def sort_tier(pk, bk):
            ph, pv = hash_keys([(pk, ones_p)])
            bh, bv = hash_keys([(bk, ones_b)])
            sk, si, cnt = build_side(bh, bv, ones_b)
            pp, bp, osel, total, ovf = probe_join(
                sk, si, cnt, ph, pv, ones_p, out_cap
            )
            osel = verify_equal([(pk, ones_p)], [(bk, ones_b)], pp, bp, osel)
            return jnp.sum(osel), ovf

        def dense_tier(pk, bk):
            ph, pv = hash_keys([(pk, ones_p)])
            bh, bv = hash_keys([(bk, ones_b)])
            table, tovf = DJ.build_table(
                DJ.slot_base_hash(bh, cap), bv, ones_b, cap
            )
            pp, bp, osel, total, ovf = DJ.probe_table(
                table, bh, DJ.slot_base_hash(ph, cap), ph, pv, ones_p,
                out_cap,
            )
            osel = verify_equal([(pk, ones_p)], [(bk, ones_b)], pp, bp, osel)
            return jnp.sum(osel), ovf | tovf

        def matmul_tier(pk, bk):
            # identity binning: build keys ARE a dense domain here, the
            # shape the executor's history-seeded cost gate promotes
            kmin = jnp.min(bk)
            ph, pv = hash_keys([(pk, ones_p)])
            bh, bv = hash_keys([(bk, ones_b)])
            table, tovf = DJ.build_table(
                DJ.slot_base_binned(bk, kmin, cap), bv, ones_b, cap
            )
            pp, bp, osel, total, ovf = DJ.probe_table(
                table, bh, DJ.slot_base_binned(pk, kmin, cap), ph, pv,
                ones_p, out_cap,
            )
            osel = verify_equal([(pk, ones_p)], [(bk, ones_b)], pp, bp, osel)
            return jnp.sum(osel), ovf | tovf

        entry: dict = {"build_rows": n, "probe_rows": npr}
        totals = {}
        for name, fn in (
            ("sort", sort_tier), ("dense", dense_tier),
            ("matmul", matmul_tier),
        ):
            jitted = jax.jit(fn)
            total, ovf = jitted(pk, bk)  # warm: compile + stage
            totals[name] = int(np.asarray(total))
            fallbacks += int(bool(np.asarray(ovf)))
            times = []
            for _ in range(3):
                t0 = time.time()
                total, ovf = jitted(pk, bk)
                _ = int(np.asarray(total))  # forces completion
                times.append(time.time() - t0)
                fallbacks += int(bool(np.asarray(ovf)))
            times.sort()
            dt = times[len(times) // 2]
            entry[f"{name}_rows_per_sec_per_chip"] = round(npr / dt)
        assert len(set(totals.values())) == 1, totals  # tiers agree
        entry["join_rows"] = totals["sort"]
        entry["dense_over_sort"] = round(
            entry["dense_rows_per_sec_per_chip"]
            / max(1, entry["sort_rows_per_sec_per_chip"]), 3,
        )
        out[f"2^{lg}"] = entry
    out["overflow_fallbacks"] = fallbacks  # graceful ladder: must be 0
    return out


def bench_star_join() -> dict:
    """TPC-DS star-shape fragment economics: the same 3-table star query
    with the dense tier on (broadcast dimension builds fused into ONE
    multiway program) vs off (pairwise, dims dispatched separately).
    Reports fused-fragment and dispatch-round-trip counts plus row
    identity between the two plans."""
    from trino_tpu.testing import DistributedQueryRunner

    sql = """
        select i.i_category, d.d_year, sum(ss.ss_ext_sales_price) as s
        from tpcds.tiny.store_sales ss
        join tpcds.tiny.item i on ss.ss_item_sk = i.i_item_sk
        join tpcds.tiny.date_dim d on ss.ss_sold_date_sk = d.d_date_sk
        group by i.i_category, d.d_year
        order by i.i_category, d.d_year
    """
    out: dict = {}
    rows = {}
    for label, dense in (("multiway", True), ("pairwise", False)):
        runner = DistributedQueryRunner()
        runner.session.set("dense_join", dense)
        res = runner.engine.execute_statement(sql, runner.session)
        ex = res.exchange_stats or {}
        out[f"{label}_fused_fragments"] = ex.get("fusedFragments", 0)
        out[f"{label}_dispatch_round_trips"] = ex.get(
            "dispatchRoundTrips", 0
        )
        if dense:
            out["join_strategies"] = sorted(
                set((ex.get("joinStrategy") or {}).values())
            )
            out["multiway_s"] = round(_median_time(runner, sql), 3)
        rows[label] = res.rows
    out["identical"] = rows["multiway"] == rows["pairwise"]
    out["fragment_delta"] = (
        out["multiway_fused_fragments"] - out["pairwise_fused_fragments"]
    )
    return out


def _percentile(samples_ms: list, p: float) -> float:
    xs = sorted(samples_ms)
    if not xs:
        return 0.0
    return round(xs[min(len(xs) - 1, int(p / 100.0 * len(xs)))], 1)


def _batched_dispatch_delta(before: dict, after: dict) -> dict:
    """Dispatch count + mean batch size from the
    ``trino_tpu_batched_dispatches_total{size}`` counter family."""
    import re

    total = 0
    weighted = 0
    for key, val in after.get("counters", {}).items():
        m = re.match(
            r'trino_tpu_batched_dispatches_total\{size="(\d+)"\}', key
        )
        if not m:
            continue
        n = int(val - before.get("counters", {}).get(key, 0))
        total += n
        weighted += int(m.group(1)) * n
    return {
        "batched_dispatches": total,
        "mean_batch_size": round(weighted / total, 2) if total else 0.0,
    }


def bench_concurrency(
    clients: int = 16, per_client: int = 3, window_ms: int = 25
) -> dict:
    """High-concurrency serving: closed- and open-loop literal-variation
    arrival over one TPC-H shape, batched (batch_window_ms>0) vs today's
    behavior (window=0) at the same offered load.

    Every concurrent result is checked bit-identical against its
    sequential run — a drift flips ``identical`` to False.
    """
    import dataclasses
    import threading

    from trino_tpu.obs.metrics import get_registry
    from trino_tpu.testing import LocalQueryRunner

    runner = LocalQueryRunner()
    runner.session.set("execution_mode", "distributed")
    q = (
        "select l_returnflag, sum(l_quantity), count(*)"
        " from tpch.tiny.lineitem where l_quantity < {}"
        " group by l_returnflag order by l_returnflag"
    )
    lits = [10 + 2 * (i % 12) for i in range(clients * per_client)]

    def session(window: int, max_size: int = None):
        s = dataclasses.replace(
            runner.session, properties=dict(runner.session.properties)
        )
        s.properties["batch_window_ms"] = window
        s.properties["batch_max_size"] = max_size or clients
        return s

    # sequential ground truth per literal (and program-cache warm-up)
    seq_rows = {
        lit: runner.engine.execute_statement(
            q.format(lit), session(0)
        ).rows
        for lit in sorted(set(lits))
    }
    drift = [0]

    def closed_loop(window: int, rounds: int, measure: bool = True) -> list:
        """Every client issues one query per round behind a barrier, so
        each round offers `clients` simultaneous arrivals."""
        lat_ms: list = []
        lock = threading.Lock()
        barrier = threading.Barrier(clients)

        def worker(c: int) -> None:
            s = session(window)
            for r in range(rounds):
                lit = lits[(r * clients + c) % len(lits)]
                barrier.wait()
                t0 = time.time()
                res = runner.engine.execute_statement(q.format(lit), s)
                dt = (time.time() - t0) * 1000.0
                with lock:
                    if measure:
                        lat_ms.append(dt)
                    if res.rows != seq_rows[lit]:
                        drift[0] += 1

        ts = [
            threading.Thread(target=worker, args=(c,))
            for c in range(clients)
        ]
        t0 = time.time()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        wall = time.time() - t0
        return lat_ms, wall

    out: dict = {"clients": clients, "window_ms": window_ms}
    base_lat, base_wall = closed_loop(0, per_client)
    out["baseline_p50_ms"] = _percentile(base_lat, 50)
    out["baseline_p99_ms"] = _percentile(base_lat, 99)
    out["baseline_qps"] = round(len(base_lat) / base_wall, 1)
    # warm round compiles the stacked K-program off the clock, exactly
    # like the single-path warm run in _median_time
    closed_loop(window_ms, 1, measure=False)
    before = get_registry().snapshot()
    bat_lat, bat_wall = closed_loop(window_ms, per_client)
    out["batched_p50_ms"] = _percentile(bat_lat, 50)
    out["batched_p99_ms"] = _percentile(bat_lat, 99)
    out["batched_qps"] = round(len(bat_lat) / bat_wall, 1)
    out.update(_batched_dispatch_delta(before, get_registry().snapshot()))

    # open-loop groups land in the small stacked-K buckets (2, 4) that
    # the closed-loop warm round never compiled — warm them off the
    # clock too, or their first-touch compile dominates the tail
    for g in (2, 4):
        barrier = threading.Barrier(g)

        def bucket_warm(c: int, _g=g, _b=barrier) -> None:
            _b.wait()
            runner.engine.execute_statement(
                q.format(lits[c]), session(500, max_size=_g)
            )

        ts = [
            threading.Thread(target=bucket_warm, args=(c,)) for c in range(g)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    # open loop: fixed-rate arrivals at the batched setting; rates chosen
    # so the faster one's inter-arrival gap (20ms) fits inside the batch
    # window and dispatches start sharing
    open_out: dict = {}
    for qps in (10, 50):
        n = min(48, qps * 2)
        lat_ms: list = []
        lock = threading.Lock()
        t_start = time.time() + 0.05

        def arrival(i: int, _qps=qps) -> None:
            wait = t_start + i / _qps - time.time()
            if wait > 0:
                time.sleep(wait)
            lit = lits[i % len(lits)]
            t0 = time.time()
            res = runner.engine.execute_statement(
                q.format(lit), session(window_ms)
            )
            dt = (time.time() - t0) * 1000.0
            with lock:
                lat_ms.append(dt)
                if res.rows != seq_rows[lit]:
                    drift[0] += 1

        before = get_registry().snapshot()
        ts = [threading.Thread(target=arrival, args=(i,)) for i in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        entry = {
            "p50_ms": _percentile(lat_ms, 50),
            "p99_ms": _percentile(lat_ms, 99),
        }
        entry.update(
            _batched_dispatch_delta(before, get_registry().snapshot())
        )
        open_out[f"qps_{qps}"] = entry
    out["open_loop"] = open_out
    out["row_drift"] = drift[0]
    out["identical"] = drift[0] == 0
    return out


def bench_open_loop(
    clients: int = 1000,
    qps: float = 2000.0,
    duration_s: float = 5.0,
    path: str = "/v1/info",
) -> dict:
    """Serving-tier open loop: ``clients`` keep-alive HTTP pollers at a
    fixed aggregate arrival rate against the event-loop front door.

    The load generator is itself a single-threaded ``selectors`` loop —
    one thread drives every connection — so the measured thread count is
    the SERVER's concurrency cost, not the harness's. Reports request
    p50/p99, achieved qps, shed counts (from the metrics registry), and
    peak process thread count (the headline: threads << clients)."""
    import selectors
    import socket
    import threading

    from trino_tpu.config import ServerConfig
    from trino_tpu.obs.metrics import get_registry
    from trino_tpu.server.http import TrinoTpuServer

    server = TrinoTpuServer(
        server_config=ServerConfig(max_connections=clients + 64)
    ).start()
    before = get_registry().snapshot()
    sel = selectors.DefaultSelector()
    request = (
        f"GET {path} HTTP/1.1\r\nHost: bench\r\n\r\n"
    ).encode()
    interval = clients / qps  # per-client inter-arrival gap
    t_start = time.time() + 0.5  # connect phase happens off the clock

    class _Poller:
        __slots__ = ("sock", "buf", "inflight", "next_at", "t0")

        def __init__(self, i: int):
            self.sock = socket.create_connection(
                (server.host, server.port), timeout=10
            )
            self.sock.setblocking(False)
            self.buf = b""
            self.inflight = False
            # stagger starts uniformly across one interval
            self.next_at = t_start + (i / clients) * interval
            self.t0 = 0.0

    pollers = [_Poller(i) for i in range(clients)]
    for p in pollers:
        sel.register(p.sock, selectors.EVENT_READ, p)

    lat_ms: list = []
    shed_in_band = 0  # 503s observed by the pollers themselves
    errors = 0
    peak_threads = threading.active_count()
    deadline = t_start + duration_s

    def _response_complete(buf: bytes):
        head_end = buf.find(b"\r\n\r\n")
        if head_end < 0:
            return None
        head = buf[:head_end].decode("iso-8859-1", "replace")
        clen = 0
        for line in head.split("\r\n")[1:]:
            if line.lower().startswith("content-length:"):
                clen = int(line.split(":", 1)[1])
        total = head_end + 4 + clen
        if len(buf) < total:
            return None
        return head.split(" ", 2)[1], buf[total:]

    now = time.time()
    while now < deadline:
        # fire every poller whose arrival time has come
        nxt = deadline
        for p in pollers:
            if not p.inflight and p.next_at <= now:
                try:
                    p.sock.sendall(request)
                except OSError:
                    errors += 1
                    p.next_at = now + interval
                    continue
                p.inflight = True
                p.t0 = now
            if not p.inflight:
                nxt = min(nxt, p.next_at)
        for key, _ in sel.select(timeout=max(0.0, min(nxt, deadline) - time.time())):
            p = key.data
            try:
                chunk = p.sock.recv(65536)
            except BlockingIOError:
                continue
            except OSError:
                errors += 1
                continue
            if not chunk:
                errors += 1
                sel.unregister(p.sock)
                continue
            p.buf += chunk
            done = _response_complete(p.buf)
            if done is not None:
                status, p.buf = done
                now2 = time.time()
                lat_ms.append((now2 - p.t0) * 1000.0)
                if status == "503":
                    shed_in_band += 1
                p.inflight = False
                # open loop: schedule from the timeline, not completion
                p.next_at = max(p.next_at + interval, now2)
        peak_threads = max(peak_threads, threading.active_count())
        now = time.time()

    for p in pollers:
        try:
            p.sock.close()
        except OSError:
            pass
    after = get_registry().snapshot()
    server.stop()
    shed_total = 0
    for k, v in after.get("counters", {}).items():
        if k.startswith("trino_tpu_requests_shed_total"):
            shed_total += int(
                v - before.get("counters", {}).get(k, 0)
            )
    wall = max(1e-9, time.time() - t_start)
    return {
        "clients": clients,
        "offered_qps": qps,
        "achieved_qps": round(len(lat_ms) / wall, 1),
        "p50_ms": _percentile(lat_ms, 50),
        "p99_ms": _percentile(lat_ms, 99),
        "requests": len(lat_ms),
        "shed_503": shed_in_band,
        "shed_counter_delta": shed_total,
        "errors": errors,
        "peak_threads": peak_threads,
        "threads_much_less_than_clients": peak_threads * 10 <= clients,
    }


def bench_result_cache(repeats: int = 15) -> dict:
    """Semantic result cache: one cold TPC-H Q1 execution stores the
    result, then ``repeats`` warm repeats must be served from the RESULT
    tier — no parse, no plan, no dispatch. Headline: warm p50 < 1 ms and
    rows bit-identical to a cache-off run."""
    from trino_tpu.benchmarks.tpch import queries as corpus
    from trino_tpu.config import Session
    from trino_tpu.testing import LocalQueryRunner

    runner = LocalQueryRunner()
    sql = corpus("tpch.tiny")[1]
    session = Session(properties={"execution_mode": "distributed",
                                  "result_cache": True})
    baseline = runner.engine.execute_statement(
        sql, Session(properties={"execution_mode": "distributed"})
    )
    t0 = time.time()
    cold = runner.engine.execute_statement(sql, session)
    cold_s = time.time() - t0
    lat_ms, hits = [], 0
    rows = None
    for _ in range(repeats):
        t0 = time.time()
        res = runner.engine.execute_statement(sql, session)
        lat_ms.append((time.time() - t0) * 1000.0)
        if (res.result_cache_stats or {}).get("resultCacheHit"):
            hits += 1
        rows = res.rows
    p50 = _percentile(lat_ms, 50)
    identical = sorted(map(tuple, rows or ())) == sorted(
        map(tuple, baseline.rows or ())
    ) and sorted(map(tuple, cold.rows or ())) == sorted(
        map(tuple, baseline.rows or ())
    )
    out = {
        "cold_s": round(cold_s, 3),
        "warm_p50_ms": p50,
        "warm_p99_ms": _percentile(lat_ms, 99),
        "hits": hits,
        "repeats": repeats,
        "identical": identical,
        "speedup": round(cold_s * 1000.0 / max(p50, 1e-6), 1),
    }
    assert p50 < 1.0, f"warm p50 {p50}ms >= 1ms"
    assert hits >= 1, "no result-cache hit observed"
    assert identical, "cached rows drifted from cache-off baseline"
    return out


def _subprocess_entry(call: str, timeout_s: int) -> dict:
    """Run ``bench_suite.<call>`` in a fresh python, hard-killed on
    timeout (a cancelled XLA compile holds the chip: the child must DIE,
    not linger)."""
    code = (
        "import json, sys; sys.path.insert(0, %r); "
        "import bench_suite as B; print('@@'+json.dumps(B.%s))"
        % (os.path.dirname(os.path.abspath(__file__)), call)
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return {"timeout": timeout_s}
    for line in proc.stdout.splitlines():
        if line.startswith("@@"):
            return json.loads(line[2:])
    tail = (proc.stderr or "").strip().splitlines()
    return {"error": tail[-1][:200] if tail else f"exit {proc.returncode}"}


def run_suite() -> dict:
    suite: dict = {}
    t0 = time.time()
    tpch: dict = {}
    for q in (1, 3, 5, 10):
        r = _subprocess_entry(f"tpch_sf1(queries=({q},))", 600)
        if "timeout" in r or "error" in r:
            tpch[f"q{q:02d}_s"] = r  # explicit per-query failure marker
        else:
            tpch.update(r)
    suite["tpch_sf1"] = tpch
    ds: dict = {}
    for q in (95, 64):
        r = _subprocess_entry(f"tpcds_q({q})", 420)
        if "timeout" in r or "error" in r:
            ds[f"q{q}_s"] = r
        else:
            ds.update(r)
    suite["tpcds"] = ds
    suite["columnar"] = _subprocess_entry("columnar_scan_rates()", 420)
    suite["parquet_table_cache"] = _subprocess_entry(
        "parquet_table_cache()", 420
    )
    suite["concurrency"] = _subprocess_entry("bench_concurrency()", 420)
    suite["open_loop_http"] = _subprocess_entry(
        "bench_open_loop(clients=200, qps=400.0, duration_s=4.0)", 120
    )
    suite["adaptive_history"] = _subprocess_entry("adaptive_history()", 420)
    suite["result_cache"] = _subprocess_entry("bench_result_cache()", 300)
    suite["join"] = _subprocess_entry("bench_join()", 600)
    suite["star_join"] = _subprocess_entry("bench_star_join()", 420)
    suite["suite_wall_s"] = round(time.time() - t0, 1)
    return suite


if __name__ == "__main__":
    print(json.dumps(run_suite()))
